"""Concurrency invariant analyzer (nydus_snapshotter_tpu/analysis/).

Two halves:

1. **planted bugs** — fixture modules written to a temp package, each
   containing exactly the defect a detector exists for (a two-lock
   cycle, a ``queue.put`` under a lock, an undocumented ``ntpu_*``
   metric, an unregistered failpoint site, an uncarried trace context
   across a Thread spawn) — every detector must fire, and the matched
   clean variants must NOT fire;
2. **the real tree** — ``tools/analyze.py`` run over the actual package
   must produce zero findings outside the reviewed baseline (the same
   gate the CI ``analyze`` job enforces), and the baseline file itself
   must be well-formed (every suppression justified, none stale).

Plus the runtime (Eraser-style) lockset detector: planted races are
caught, lock-discipline-clean accesses are not, runtime lock-order
cycles are recorded, and the instrumented wrappers compose with
``threading.Condition``.
"""

from __future__ import annotations

import os
import textwrap
import threading

import pytest

from nydus_snapshotter_tpu.analysis import baseline as baseline_mod
from nydus_snapshotter_tpu.analysis import runtime as an_rt
from nydus_snapshotter_tpu.analysis.drift import (
    find_config_drift,
    find_failpoint_drift,
    find_metric_drift,
    find_trace_carry_drift,
)
from nydus_snapshotter_tpu.analysis.locks import (
    find_blocking_findings,
    find_lock_order_findings,
)
from nydus_snapshotter_tpu.analysis.package import PackageModel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_pkg(root, files: dict[str, str]) -> str:
    pkg = os.path.join(str(root), "fixtures")
    os.makedirs(pkg, exist_ok=True)
    open(os.path.join(pkg, "__init__.py"), "w").close()
    for rel, src in files.items():
        path = os.path.join(pkg, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write(textwrap.dedent(src))
    os.makedirs(os.path.join(str(root), "docs"), exist_ok=True)
    return str(root)


class TestPlantedLockBugs:
    def test_two_lock_cycle_detected(self, tmp_path):
        root = _write_pkg(tmp_path, {"bugs.py": """
            import threading

            class A:
                def __init__(self):
                    self._la = threading.Lock()
                    self._lb = threading.Lock()

                def one(self):
                    with self._la:
                        with self._lb:
                            pass

                def two(self):
                    with self._lb:
                        with self._la:
                            pass
            """})
        model = PackageModel(root, "fixtures")
        found = find_lock_order_findings(model)
        assert any("inversion" in f.detail and "_la" in f.detail for f in found), found

    def test_interprocedural_cycle_detected(self, tmp_path):
        root = _write_pkg(tmp_path, {"bugs.py": """
            import threading

            class A:
                def __init__(self):
                    self._la = threading.Lock()
                    self._lb = threading.Lock()

                def fwd(self):
                    with self._la:
                        self._grab_b()

                def _grab_b(self):
                    with self._lb:
                        pass

                def rev(self):
                    with self._lb:
                        self._grab_a()

                def _grab_a(self):
                    with self._la:
                        pass
            """})
        found = find_lock_order_findings(PackageModel(root, "fixtures"))
        assert any("inversion" in f.detail for f in found), found

    def test_self_reacquire_detected(self, tmp_path):
        root = _write_pkg(tmp_path, {"bugs.py": """
            import threading

            class A:
                def __init__(self):
                    self._lock = threading.Lock()

                def boom(self):
                    with self._lock:
                        self._again()

                def _again(self):
                    with self._lock:
                        pass
            """})
        found = find_lock_order_findings(PackageModel(root, "fixtures"))
        assert any(f.detail.startswith("self:") for f in found), found

    def test_rlock_reacquire_not_flagged(self, tmp_path):
        root = _write_pkg(tmp_path, {"ok.py": """
            import threading

            class A:
                def __init__(self):
                    self._lock = threading.RLock()

                def fine(self):
                    with self._lock:
                        self._again()

                def _again(self):
                    with self._lock:
                        pass
            """})
        found = find_lock_order_findings(PackageModel(root, "fixtures"))
        assert not found, found

    def test_consistent_order_not_flagged(self, tmp_path):
        root = _write_pkg(tmp_path, {"ok.py": """
            import threading

            class A:
                def __init__(self):
                    self._la = threading.Lock()
                    self._lb = threading.Lock()

                def one(self):
                    with self._la:
                        with self._lb:
                            pass

                def two(self):
                    with self._la:
                        with self._lb:
                            pass
            """})
        found = find_lock_order_findings(PackageModel(root, "fixtures"))
        assert not found, found

    def test_queue_put_under_lock_detected(self, tmp_path):
        root = _write_pkg(tmp_path, {"bugs.py": """
            import queue
            import threading

            class B:
                def __init__(self):
                    self._q = queue.Queue(maxsize=4)
                    self._lock = threading.Lock()

                def send(self, item):
                    with self._lock:
                        self._q.put(item)

                def ok_send(self, item):
                    self._q.put(item)
            """})
        found = find_blocking_findings(PackageModel(root, "fixtures"))
        assert len(found) == 1 and found[0].qualname == "B.send", found
        assert found[0].detail.startswith("queue.put"), found

    def test_future_result_under_contextmanager_lock_detected(self, tmp_path):
        # The metastore shape: a generator contextmanager holds the lock
        # at its yield; a join inside the with-block blocks under it.
        root = _write_pkg(tmp_path, {"bugs.py": """
            import threading
            from contextlib import contextmanager

            class C:
                def __init__(self):
                    self._wlock = threading.Lock()

                @contextmanager
                def txn(self):
                    self._wlock.acquire()
                    try:
                        yield
                    finally:
                        self._wlock.release()

                def join_under_txn(self, fut):
                    with self.txn():
                        fut.result()

                def ok_join(self, fut):
                    with self.txn():
                        pass
                    fut.result()
            """})
        found = find_blocking_findings(PackageModel(root, "fixtures"))
        assert len(found) == 1 and found[0].qualname == "C.join_under_txn", found

    def test_cv_wait_on_own_condition_excused(self, tmp_path):
        root = _write_pkg(tmp_path, {"ok.py": """
            import threading

            class W:
                def __init__(self):
                    self._cv = threading.Condition()
                    self._items = []

                def pop(self):
                    with self._cv:
                        while not self._items:
                            self._cv.wait()
                        return self._items.pop()
            """})
        found = find_blocking_findings(PackageModel(root, "fixtures"))
        assert not found, found

    def test_cv_wait_with_second_lock_held_flagged(self, tmp_path):
        root = _write_pkg(tmp_path, {"bugs.py": """
            import threading

            class W:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._cv = threading.Condition()

                def bad_wait(self):
                    with self._mu:
                        with self._cv:
                            self._cv.wait()
            """})
        found = find_blocking_findings(PackageModel(root, "fixtures"))
        assert any(
            f.qualname == "W.bad_wait" and "_mu" in f.message for f in found
        ), found


class TestPlantedDriftBugs:
    def test_undocumented_metric_detected(self, tmp_path):
        root = _write_pkg(tmp_path, {"met.py": """
            from nydus_snapshotter_tpu.metrics.registry import Counter

            BOGUS = Counter("ntpu_bogus_total", "planted undocumented metric")
            GOOD = Counter("ntpu_documented_total", "documented metric")
            """})
        with open(os.path.join(root, "docs", "obs.md"), "w") as f:
            f.write("We export `ntpu_documented_total` and nothing else.\n")
        found = find_metric_drift(PackageModel(root, "fixtures"), root)
        assert [f.qualname for f in found] == ["ntpu_bogus_total"], found

    def test_stale_doc_metric_detected(self, tmp_path):
        root = _write_pkg(tmp_path, {"met.py": "x = 1\n"})
        with open(os.path.join(root, "docs", "obs.md"), "w") as f:
            f.write("Watch `ntpu_ghost_total` closely.\n")
        found = find_metric_drift(PackageModel(root, "fixtures"), root)
        assert any(f.detail == "stale-doc:ntpu_ghost_total" for f in found), found

    def test_unregistered_and_undocumented_failpoint_detected(self, tmp_path):
        root = _write_pkg(tmp_path, {
            "failpoint/__init__.py": """
                KNOWN_SITES = ("a.known",)

                def hit(site):
                    pass
                """,
            "mod.py": """
                from fixtures import failpoint

                def work():
                    failpoint.hit("a.known")
                    failpoint.hit("b.rogue")
                """,
        })
        with open(os.path.join(root, "docs", "robustness.md"), "w") as f:
            f.write("no sites documented here\n")
        found = find_failpoint_drift(PackageModel(root, "fixtures"), root)
        details = {f.detail for f in found}
        assert "unregistered:b.rogue" in details, found
        assert "undocumented:a.known" in details, found
        assert "untested:a.known" in details, found  # no tests/ dir in fixture

    def test_undocumented_config_key_detected(self, tmp_path):
        root = _write_pkg(tmp_path, {"config/config.py": """
            from dataclasses import dataclass, field

            @dataclass
            class FooConfig:
                mystery_knob: int = 7
                documented_knob: int = 1

            @dataclass
            class SnapshotterConfig:
                foo: FooConfig = field(default_factory=FooConfig)
            """})
        with open(os.path.join(root, "docs", "configure.md"), "w") as f:
            f.write("## `[foo]`\n\n| `documented_knob` | 1 |\n")
        os.makedirs(os.path.join(root, "misc", "snapshotter"), exist_ok=True)
        with open(os.path.join(root, "misc", "snapshotter", "config.toml"), "w") as f:
            f.write("[foo]\ndocumented_knob = 1\n# mystery_knob = 7\n")
        found = find_config_drift(PackageModel(root, "fixtures"), root)
        assert [f.detail for f in found] == ["key-undocumented:foo.mystery_knob"], found

    def test_uncarried_trace_context_detected(self, tmp_path):
        root = _write_pkg(tmp_path, {"spawny.py": """
            import threading

            from nydus_snapshotter_tpu import trace

            def worker():
                with trace.span("fixture.op"):
                    pass

            def spawn_uncarried():
                t = threading.Thread(target=worker)
                t.start()
                return t

            def carried_worker(ctx):
                with trace.with_context(ctx), trace.span("fixture.op"):
                    pass

            def spawn_carried():
                ctx = trace.capture()
                t = threading.Thread(target=lambda: carried_worker(ctx))
                t.start()
                return t

            def untraced_worker():
                return 2 + 2

            def spawn_untraced():
                t = threading.Thread(target=untraced_worker)
                t.start()
                return t
            """})
        found = find_trace_carry_drift(PackageModel(root, "fixtures"))
        assert len(found) == 1 and found[0].qualname == "spawn_uncarried", found


class TestRealTree:
    def test_zero_new_findings_with_reviewed_baseline(self):
        """The CI gate, as a tier-1 test: the actual package has no
        analyzer findings outside analysis/baseline.toml, every
        suppression is justified, and none are stale."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "ntpu_tools_analyze", os.path.join(REPO, "tools", "analyze.py")
        )
        tool = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tool)

        rep = tool.run(REPO)
        baseline = baseline_mod.load_baseline()  # raises on missing justification
        rep.apply_baseline(baseline)
        assert not rep.findings, "new analyzer findings:\n" + "\n".join(
            f.render() for f in rep.findings
        )
        assert not rep.stale_suppressions, rep.stale_suppressions

    def test_every_known_failpoint_site_is_chaos_covered(self):
        """Kept alongside the drift gate on purpose: the failpoint drift
        detector over the real tree must stay finding-free (registered ==
        fired == documented == tested)."""
        model = PackageModel(REPO, "nydus_snapshotter_tpu")
        assert not find_failpoint_drift(model, REPO)

    def test_baseline_requires_justification(self, tmp_path):
        bad = tmp_path / "baseline.toml"
        bad.write_text('[[suppress]]\nid = "x:y:z:w"\njustification = ""\n')
        with pytest.raises(baseline_mod.BaselineError):
            baseline_mod.load_baseline(str(bad))


class TestLocksetRuntime:
    @pytest.fixture(autouse=True)
    def _enabled(self):
        an_rt.reset()
        an_rt.enable(True)
        yield
        an_rt.enable(
            os.environ.get("NTPU_ANALYZE", "") not in ("", "0", "off", "false")
        )
        an_rt.reset()

    def test_planted_unlocked_write_race_detected(self):
        def w():
            for _ in range(200):
                an_rt.note_write("planted.counter")

        ts = [threading.Thread(target=w) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert any(r["var"] == "planted.counter" for r in an_rt.races())

    def test_lock_disciplined_access_is_clean(self):
        lk = an_rt.make_lock("guard")

        def w():
            for _ in range(200):
                with lk:
                    an_rt.note_write("guarded.counter")

        ts = [threading.Thread(target=w) for _ in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not an_rt.races(), an_rt.races()

    def test_reader_writer_with_common_lock_is_clean(self):
        lk = an_rt.make_lock("rw")
        extra = an_rt.make_lock("extra")

        def w():
            for _ in range(100):
                with lk:
                    an_rt.note_write("rw.var")

        def r():
            for _ in range(100):
                with extra:
                    with lk:
                        an_rt.note_read("rw.var")

        ts = [threading.Thread(target=w), threading.Thread(target=r)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # Locksets intersect to {rw}, never empty.
        assert not an_rt.races(), an_rt.races()

    def test_runtime_lock_order_cycle_detected(self):
        a = an_rt.make_lock("order.A")
        b = an_rt.make_lock("order.B")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        v = an_rt.order_violations()
        assert v and sorted(v[0]["locks"]) == ["order.A", "order.B"], v

    def test_consistent_runtime_order_is_clean(self):
        a = an_rt.make_lock("c.A")
        b = an_rt.make_lock("c.B")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert not an_rt.order_violations()

    def test_condition_over_instrumented_lock(self):
        lk = an_rt.make_lock("cv.lock")
        cv = an_rt.make_condition("cv", lk)
        hits = []

        def consumer():
            with cv:
                while not hits:
                    cv.wait(timeout=5)
                an_rt.note_write("cv.shared")

        t = threading.Thread(target=consumer)
        t.start()
        with cv:
            an_rt.note_write("cv.shared")
            hits.append(1)
            cv.notify()
        t.join(timeout=5)
        assert not t.is_alive()
        assert not an_rt.races(), an_rt.races()

    def test_rlock_reentry(self):
        rl = an_rt.make_rlock("re.lock")
        with rl:
            with rl:
                an_rt.note_write("re.var")
            # still held after inner release
            an_rt.note_write("re.var")
        assert not an_rt.races()

    def test_disabled_factories_return_plain_primitives(self):
        an_rt.enable(False)
        assert type(an_rt.make_lock("x")) is type(threading.Lock())
        assert isinstance(
            an_rt.make_condition("x"), threading.Condition
        )

    def test_report_renders_findings(self):
        an_rt.note_write("rep.var")

        def other():
            an_rt.note_write("rep.var")

        t = threading.Thread(target=other)
        t.start()
        t.join()
        text = an_rt.report()
        assert "rep.var" in text


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
