"""Concurrency stress: kill storms and parallel load on the threaded state
machines.

The reference runs everything under ``go test -race`` and harvests GORACE
reports in e2e (/root/reference/Makefile:150-169,
integration/entrypoint.sh:34-48). CPython has no race detector; the
equivalent discipline here is hammering the heavily-threaded components —
manager restart/failover, the supervisor's state/fd exchange, tarfs's
semaphore+LRU pipeline — with parallel load plus kill injection, under
faulthandler (a hung test dumps every thread's stack instead of timing out
silently).
"""

import faulthandler
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

faulthandler.enable()

from nydus_snapshotter_tpu import constants
from nydus_snapshotter_tpu.manager.manager import Manager
from nydus_snapshotter_tpu.rafs.rafs import Rafs
from nydus_snapshotter_tpu.store.database import Database
from nydus_snapshotter_tpu.supervisor.supervisor import Supervisor

from tests.test_daemon_lifecycle import (
    _build_image,
    _daemon_config_json,
    _mk_config,
)

RNG = np.random.default_rng(0x57E55)


class TestManagerKillStorm:
    def test_reads_survive_repeated_sigkill_restart(self, tmp_path):
        """Reader threads hammer the daemon while it is repeatedly
        SIGKILLed; the restart policy must bring mounts back and every
        read must either succeed with correct bytes or fail cleanly —
        no wrong data, no deadlock, no unraised thread exception."""
        boot, blob_dir, files = _build_image(tmp_path)
        cfg = _mk_config(tmp_path, policy=constants.RECOVER_POLICY_RESTART)
        mgr = Manager(cfg, Database(cfg.database_path))
        daemon = mgr.new_daemon("storm")
        mgr.add_daemon(daemon)
        errors: list[BaseException] = []
        wrong: list[str] = []
        stop = threading.Event()
        want = files["/app/data.bin"]

        def reader(tid: int):
            import http.client

            from nydus_snapshotter_tpu.daemon.client import ClientError
            from nydus_snapshotter_tpu.utils import errdefs

            # Expected while the daemon is down or replaying mounts:
            # connection refused/reset (OSError), a request cut mid-body
            # (HTTPException/IncompleteRead), the API answering before the
            # instance is remounted (NotFound and other errdefs), or any
            # mapped API error (ClientError). Anything else is a real bug.
            transient = (
                ClientError, OSError, http.client.HTTPException, errdefs.NydusError,
            )
            while not stop.is_set():
                try:
                    got = daemon.client().read_file("/snap1", "/app/data.bin")
                    if got != want:
                        wrong.append(f"t{tid}: {len(got)} bytes")
                except transient:
                    # transient: daemon mid-restart; must never wedge
                    time.sleep(0.02)
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
                    return

        try:
            mgr.start_daemon(daemon)
            # Real replay layout: the restart policy remounts from the
            # snapshot dir's fs/image/image.boot plus the persisted
            # per-instance config in the daemon workdir.
            snap_dir = tmp_path / "snapdir"
            img_dir = snap_dir / "fs" / "image"
            img_dir.mkdir(parents=True)
            with open(boot, "rb") as f:
                (img_dir / "image.boot").write_bytes(f.read())
            rafs = Rafs(
                snapshot_id="snap1", daemon_id="storm", snapshot_dir=str(snap_dir)
            )
            daemon.shared_mount(rafs, boot, _daemon_config_json(blob_dir))
            with open(os.path.join(daemon.states.workdir, "snap1.json"), "w") as f:
                f.write(_daemon_config_json(blob_dir))
            mgr.monitor.run()
            mgr.run_death_handler()

            threads = [
                threading.Thread(target=reader, args=(i,), daemon=True)
                for i in range(4)
            ]
            for t in threads:
                t.start()

            for round_no in range(3):
                pid = daemon.pid
                os.kill(pid, signal.SIGKILL)
                # wait for the restart policy to bring a NEW pid up and
                # the mount to answer again
                deadline = time.time() + 30
                ok = False
                while time.time() < deadline:
                    try:
                        if (
                            daemon.pid != pid
                            and daemon.client().read_file("/snap1", "/app/hello.txt")
                            == files["/app/hello.txt"]
                        ):
                            ok = True
                            break
                    except Exception:
                        pass
                    time.sleep(0.1)
                assert ok, f"round {round_no}: daemon never recovered"

            stop.set()
            for t in threads:
                t.join(timeout=5)
                assert not t.is_alive(), "reader thread wedged"
            assert not wrong, f"corrupt reads: {wrong[:3]}"
            assert not errors
        finally:
            stop.set()
            try:
                mgr.destroy_daemon(daemon)
            except Exception:
                pass
            mgr.stop()


class TestSupervisorHammer:
    def test_parallel_pushes_and_takeovers(self, tmp_path):
        """Many writers pushing state+fds interleaved with takeover reads:
        the supervisor must never crash, never hand out a stale mix, and
        must not leak fds."""
        sup = Supervisor("hammer", str(tmp_path / "s.sock"))
        sup.start()
        import socket as socketmod

        errors: list[BaseException] = []

        def fd_count() -> int:
            return len(os.listdir("/proc/self/fd"))

        def push(tid: int):
            try:
                for i in range(25):
                    payload = json.dumps({"id": "d", "tid": tid, "i": i}).encode()
                    r, w = os.pipe()
                    try:
                        with socketmod.socket(
                            socketmod.AF_UNIX, socketmod.SOCK_STREAM
                        ) as s:
                            s.connect(sup.sock_path)
                            socketmod.send_fds(s, [payload], [r, w])
                    finally:
                        os.close(r)
                        os.close(w)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        def take(tid: int):
            try:
                for _ in range(25):
                    with socketmod.socket(
                        socketmod.AF_UNIX, socketmod.SOCK_STREAM
                    ) as s:
                        s.connect(sup.sock_path)
                        s.sendall(b"TAKEOVER")
                        msg, fds, _fl, _ad = socketmod.recv_fds(s, 1 << 16, 16)
                        for fd in fds:
                            os.close(fd)
                        if msg and msg != b"{}":
                            rec = json.loads(msg)
                            assert rec["id"] == "d"
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        before = fd_count()
        threads = [
            threading.Thread(target=push, args=(i,), daemon=True) for i in range(4)
        ] + [threading.Thread(target=take, args=(i,), daemon=True) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "supervisor client thread wedged"
        assert not errors, errors[:3]
        sup.stop()
        # the supervisor held at most one saved session (2 fds) at a time;
        # after stop everything must be returned to the baseline (small
        # slack for the test runner's own churn)
        assert fd_count() <= before + 4


class TestTarfsParallelPrepare:
    def test_concurrent_layers_respect_limiter_and_complete(
        self, tmp_path, monkeypatch
    ):
        """N layers prepared concurrently for one ref with a 2-wide
        semaphore: all complete, peak concurrency never exceeds the limit,
        and the LRU/singleflight caches stay consistent."""
        import gzip as gzipmod

        from nydus_snapshotter_tpu.remote.remote import Remote
        from nydus_snapshotter_tpu.tarfs.tarfs import Manager as TarfsManager

        from tests.test_remote import FakeRegistry
        from tests.test_tarfs import make_tar, publish_image, snap_labels

        orig = Remote.__init__

        def patched(self, keychain=None, insecure=False):
            orig(self, keychain=keychain, insecure=insecure)
            self.with_plain_http = True

        monkeypatch.setattr(Remote, "__init__", patched)

        reg = FakeRegistry(require_auth=False)
        try:
            n_layers = 8
            layers = [
                {f"etc/f{i}": RNG.integers(0, 256, 30_000, dtype=np.uint8).tobytes()}
                for i in range(n_layers)
            ]
            mdigest, layer_digests = publish_image(reg, layers)
            mgr = TarfsManager(
                cache_dir_path=str(tmp_path / "cache"), max_concurrent_process=2
            )

            active = threading.Semaphore(0)
            peak = [0]
            cur = [0]
            lock = threading.Lock()
            # Count concurrency inside the limited region (the semaphore is
            # acquired within _blob_process, so wrapping that would count
            # threads still waiting for a slot).
            orig_gen = mgr._generate_bootstrap

            def counting_gen(*a, **kw):
                with lock:
                    cur[0] += 1
                    peak[0] = max(peak[0], cur[0])
                try:
                    time.sleep(0.05)  # widen the overlap window
                    return orig_gen(*a, **kw)
                finally:
                    with lock:
                        cur[0] -= 1

            mgr._generate_bootstrap = counting_gen

            def prep(i: int):
                upper = tmp_path / "snap" / str(i) / "fs"
                upper.mkdir(parents=True)
                mgr.prepare_layer(
                    snap_labels(reg, mdigest, layer_digests[i]), str(i), str(upper)
                )

            threads = [
                threading.Thread(target=prep, args=(i,), daemon=True)
                for i in range(n_layers)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
                assert not t.is_alive(), "prepare thread wedged"
            for i in range(n_layers):
                mgr.wait_layer_ready(str(i), timeout=60)
            assert peak[0] <= 2, f"semaphore breached: peak {peak[0]}"
            for i, ld in enumerate(layer_digests):
                assert os.path.exists(mgr.layer_tar_file_path(ld.split(":")[1])), i
        finally:
            reg.close()


class TestBlobCacheRace:
    def test_parallel_reads_during_close(self, tmp_path):
        """Readers hammer a CachedBlob while it is closed mid-flight: every
        read either returns correct bytes or raises OSError — never EBADF
        crashes on recycled fds, never wrong data."""
        from nydus_snapshotter_tpu.daemon.blobcache import CachedBlob

        blob = RNG.integers(0, 256, 2_000_000, dtype=np.uint8).tobytes()

        def fetch(off, size):
            time.sleep(0.001)  # widen the race window
            return blob[off : off + size]

        for round_no in range(5):
            cached = CachedBlob(str(tmp_path / f"c{round_no}"), "ab" * 32, fetch)
            errors = []
            wrong = []
            stop = threading.Event()

            def reader(tid):
                rng = np.random.default_rng(tid)
                while not stop.is_set():
                    off = int(rng.integers(0, len(blob) - 4096))
                    try:
                        got = cached.read_at(off, 4096)
                        if got != blob[off : off + 4096]:
                            wrong.append((tid, off))
                            return
                    except OSError:
                        return  # closed underneath us: the designed outcome
                    except BaseException as e:  # noqa: BLE001
                        errors.append(e)
                        return

            threads = [
                threading.Thread(target=reader, args=(i,), daemon=True)
                for i in range(6)
            ]
            for t in threads:
                t.start()
            time.sleep(0.05)
            cached.close()
            stop.set()
            for t in threads:
                t.join(timeout=5)
                assert not t.is_alive()
            assert not errors, errors[:2]
            assert not wrong, wrong[:2]
