"""Extent-packed per-device convert sharding (ops/mesh_pack +
__graft_entry__.sharded_convert_step).

Property under test: repartitioning the pass-2 gather onto per-device
byte shards (plus the read-span halo) changes WHERE bytes live and
nothing else — cuts, digests and the emitted bootstrap stay byte-
identical to both the legacy replicated-operand arm and the
single-device host oracle, at every mesh size, while no device ever
holds more than corpus/devices + halo bytes of the corpus.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402
from nydus_snapshotter_tpu.ops import fused_convert, mesh_pack  # noqa: E402
from nydus_snapshotter_tpu.ops.chunker import ChunkDigestEngine  # noqa: E402
from nydus_snapshotter_tpu.parallel import mesh as mesh_lib  # noqa: E402

CHUNK = 0x1000


def _mk_files(seed: int, n: int, scale: int = 8192) -> list[bytes]:
    rng = np.random.default_rng(seed)
    return [
        rng.integers(
            0, 256, int(rng.integers(1, 5)) * scale + int(rng.integers(0, 997)),
            dtype=np.uint8,
        ).tobytes()
        for _ in range(n)
    ]


def _oracle(files):
    eng = ChunkDigestEngine(chunk_size=CHUNK, backend="numpy", digest_backend="numpy")
    truth = eng.process_many(files)
    cuts = [
        np.asarray([m.offset + m.size for m in metas], dtype=np.int64)
        for metas in truth
    ]
    digs = [[m.digest for m in metas] for metas in truth]
    return cuts, digs


def _plan_for(files, n_devices, chunk=CHUNK):
    eng = fused_convert.FusedDeviceEngine(chunk_size=chunk)
    table = []
    total = 0
    for f in files:
        table.append((total, len(f)))
        total += len(f)
    cuts, _ = _oracle(files)
    buckets, order = eng.plan_buckets(table, cuts)
    plan = mesh_pack.plan_mesh_pack(
        buckets, order, total, n_devices, halo_bytes=eng.max_read_span()
    )
    return plan, buckets, order, total


class TestPlanner:
    """Host-side geometry: pure numpy, no mesh involved."""

    def test_local_offsets_and_devices(self):
        files = _mk_files(1, 6)
        n = 4
        plan, buckets, order, total = _plan_for(files, n)
        assert plan.shard_bytes == -(-total // n)
        assert plan.pack_len == plan.shard_bytes + plan.halo_bytes
        for b, sb in zip(buckets, plan.buckets):
            assert sum(sb.counts) == b.count
            for d in range(n):
                lo = d * sb.rows_per_device
                for i in range(sb.counts[d]):
                    row = lo + i
                    off = int(sb.offsets_abs[row])
                    assert plan.device_of(off) == d
                    assert sb.offsets_local[row] == off - d * plan.shard_bytes
                    # the no-clamp invariant: every gather fits the slab
                    assert (
                        sb.offsets_local[row] + sb.cap_blocks * 64 <= plan.pack_len
                    )

    def test_order_covers_every_chunk_once(self):
        files = _mk_files(2, 5)
        n = 8
        plan, buckets, _order, _total = _plan_for(files, n)
        n_chunks = sum(b.count for b in buckets)
        assert len(plan.order) == n_chunks
        seen = set()
        for cap, row in plan.order:
            assert (cap, row) not in seen
            seen.add((cap, row))
            sb = next(b for b in plan.buckets if b.cap_blocks == cap)
            d, i = divmod(row, sb.rows_per_device)
            assert i < sb.counts[d], "order points at a padding row"

    def test_pack_buffers_shard_plus_halo(self):
        files = _mk_files(3, 4)
        n = 4
        plan, _b, _o, total = _plan_for(files, n)
        buf = np.frombuffer(b"".join(files), dtype=np.uint8)
        packed = mesh_pack.pack_buffers(buf, plan)
        assert packed.shape == (n, plan.pack_len)
        S = plan.shard_bytes
        for d in range(n):
            lo = d * S
            hi = min(lo + plan.pack_len, total)
            want = buf[lo:hi]
            assert (packed[d, : hi - lo] == want).all()
            assert (packed[d, hi - lo :] == 0).all()

    def test_chunk_spanning_shard_cut_stays_whole(self):
        """A chunk whose bytes straddle k*S must be gatherable entirely
        from device k's slab — that is the halo rule."""
        files = _mk_files(4, 6)
        n = 4
        plan, buckets, _o, total = _plan_for(files, n)
        S = plan.shard_bytes
        straddlers = 0
        for b in buckets:
            for off, size in zip(b.offsets[: b.count], b.sizes[: b.count]):
                d = plan.device_of(int(off))
                if int(off) + int(size) > (d + 1) * S:
                    straddlers += 1
                    assert int(off) - d * S + b.cap_blocks * 64 <= plan.pack_len
        assert straddlers > 0, "corpus produced no shard-cut straddler; enlarge it"

    def test_unordered_bucket_rejected(self):
        b = fused_convert.Bucket(
            cap_blocks=2,
            offsets=np.asarray([500, 100], np.int32),
            sizes=np.asarray([64, 64], np.int32),
            count=2,
        )
        with pytest.raises(ValueError, match="offset-ordered"):
            mesh_pack.plan_mesh_pack([b], [(2, 0), (2, 1)], 600, 2)

    def test_more_devices_than_bytes(self):
        b = fused_convert.Bucket(
            cap_blocks=1,
            offsets=np.asarray([0, 2], np.int32),
            sizes=np.asarray([2, 3], np.int32),
            count=2,
        )
        plan = mesh_pack.plan_mesh_pack([b], [(1, 0), (1, 1)], 5, 8)
        assert plan.shard_bytes == 1
        devs = [plan.device_of(0), plan.device_of(2)]
        assert devs == [0, 2]
        assert sum(plan.buckets[0].counts) == 2


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
class TestByteIdentity:
    """extent == replicated == host oracle across mesh sizes."""

    def test_convert_identity_and_bytes_bound(self, n_devices):
        files = _mk_files(10 + n_devices, max(2, n_devices))
        mesh = mesh_lib.make_mesh(n_devices)
        rep_e: dict = {}
        cuts_e, digs_e, boot_e = graft.sharded_convert_step(
            files, CHUNK, n_devices, mesh, pack="extent", report=rep_e
        )
        rep_r: dict = {}
        cuts_r, digs_r, boot_r = graft.sharded_convert_step(
            files, CHUNK, n_devices, mesh, pack="replicated", report=rep_r
        )
        cuts_t, digs_t = _oracle(files)
        for a, b, t in zip(cuts_e, cuts_r, cuts_t):
            assert (np.asarray(a) == t).all()
            assert (np.asarray(b) == t).all()
        assert digs_e == digs_t
        assert digs_r == digs_t
        assert boot_e == boot_r
        # the no-replication gate, and proof the gate DETECTS replication
        assert rep_e["max_device_bytes"] <= rep_e["bound_bytes"]
        if n_devices > 1:
            assert rep_r["max_device_bytes"] > rep_e["bound_bytes"], (
                "replicated arm should trip the addressable-bytes bound"
            )


class TestEdgeCases:
    def test_empty_file_in_batch(self):
        files = [b"", _mk_files(20, 1)[0], b""]
        mesh = mesh_lib.make_mesh(2)
        cuts, digs, boot = graft.sharded_convert_step(
            files, CHUNK, 2, mesh, pack="extent"
        )
        cuts_t, digs_t = _oracle(files)
        assert [len(c) for c in cuts] == [0, len(cuts_t[1]), 0]
        assert digs == digs_t

    def test_all_empty_batch(self):
        mesh = mesh_lib.make_mesh(2)
        cuts, digs, boot = graft.sharded_convert_step(
            [b"", b""], CHUNK, 2, mesh, pack="extent"
        )
        assert digs == [[], []]
        assert isinstance(boot, bytes) and boot

    def test_files_smaller_than_one_extent(self):
        # every file far below shard_bytes: chunks cluster on low devices,
        # the plan must still cover all of them and stay byte-identical
        rng = np.random.default_rng(7)
        files = [
            rng.integers(0, 256, int(rng.integers(1100, 2500)), np.uint8).tobytes()
            for _ in range(5)
        ]
        mesh = mesh_lib.make_mesh(8)
        rep: dict = {}
        cuts, digs, _boot = graft.sharded_convert_step(
            files, CHUNK, 8, mesh, pack="extent", report=rep
        )
        cuts_t, digs_t = _oracle(files)
        assert digs == digs_t
        assert rep["max_device_bytes"] <= rep["bound_bytes"]

    def test_env_pack_override(self, monkeypatch):
        monkeypatch.setenv("NTPU_MESH_PACK", "replicated")
        assert mesh_pack.resolve_mesh_config().pack == "replicated"
        files = _mk_files(30, 2)
        mesh = mesh_lib.make_mesh(2)
        rep: dict = {}
        graft.sharded_convert_step(files, CHUNK, 2, mesh, report=rep)
        assert rep["pack"] == "replicated"
        monkeypatch.setenv("NTPU_MESH_PACK", "extent")
        rep2: dict = {}
        graft.sharded_convert_step(files, CHUNK, 2, mesh, report=rep2)
        assert rep2["pack"] == "extent"

    def test_env_halo_override(self, monkeypatch):
        monkeypatch.setenv("NTPU_MESH_HALO_KIB", "64")
        files = _mk_files(31, 2)
        mesh = mesh_lib.make_mesh(2)
        rep: dict = {}
        cuts, digs, _ = graft.sharded_convert_step(
            files, CHUNK, 2, mesh, pack="extent", report=rep
        )
        assert rep["halo_bytes"] >= 64 << 10
        _cuts_t, digs_t = _oracle(files)
        assert digs == digs_t
