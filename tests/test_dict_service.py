"""Shared chunk-dict service: RPC round trips, converter byte-identity,
cross-converter dedup, namespaces, trace propagation and chaos."""

import io
import json
import os
import tarfile

import numpy as np
import pytest

from nydus_snapshotter_tpu import failpoint, trace
from nydus_snapshotter_tpu.converter.batch import BatchConverter
from nydus_snapshotter_tpu.converter.types import ConvertError, PackOption
from nydus_snapshotter_tpu.parallel.dict_service import (
    DictClient,
    DictService,
    DictServiceError,
    ServiceChunkDict,
    ServiceDict,
    open_chunk_dict,
    resolve_dict_config,
)

RNG = np.random.default_rng(17)
POOL = [
    RNG.integers(0, 256, int(RNG.integers(4_000, 80_000)), dtype=np.uint8).tobytes()
    for _ in range(24)
]


def mk_image(seed: int, layers: int = 2, files: int = 6) -> list[bytes]:
    r = np.random.default_rng(seed)
    out = []
    for _li in range(layers):
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w", format=tarfile.GNU_FORMAT) as tf:
            for fi in range(files):
                data = POOL[int(r.integers(0, len(POOL)))]
                ti = tarfile.TarInfo(f"d/f{seed}_{fi}")
                ti.size = len(data)
                tf.addfile(ti, io.BytesIO(data))
        out.append(buf.getvalue())
    return out


OPT = PackOption(chunk_size=0x10000, chunking="cdc")


@pytest.fixture()
def service(tmp_path):
    svc = DictService()
    svc.run(str(tmp_path / "dict.sock"))
    yield svc
    svc.stop()


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoint.clear()
    yield
    failpoint.clear()


class TestServiceRPC:
    def test_probe_merge_stats_roundtrip(self, service):
        cli = DictClient(service.sock_path)
        bc = BatchConverter(OPT)
        res = bc.convert_image("img", mk_image(1))
        out = cli.merge(res.bootstrap, "ns1")
        assert out["added"] > 0
        assert out["epoch"] == 1
        st = cli.stats("ns1")
        assert st["chunks"] == out["chunks"] == len(bc.dict)
        digs = [c.digest for c in bc.dict.bootstrap.chunks]
        ans = cli.probe(digs, "ns1")
        assert np.array_equal(ans, np.arange(len(digs)))
        miss = [bytes(RNG.integers(0, 256, 32, dtype=np.uint8)) for _ in range(5)]
        assert (cli.probe(miss, "ns1") == -1).all()

    def test_merge_is_idempotent_per_digest(self, service):
        cli = DictClient(service.sock_path)
        bc = BatchConverter(OPT)
        res = bc.convert_image("img", mk_image(2))
        first = cli.merge(res.bootstrap, "ns")
        again = cli.merge(res.bootstrap, "ns")
        assert again["added"] == 0
        assert again["chunks"] == first["chunks"]
        assert again["epoch"] == first["epoch"]  # no-op merges bump nothing

    def test_namespaces_are_isolated(self, service):
        cli = DictClient(service.sock_path)
        bc = BatchConverter(OPT)
        res = bc.convert_image("img", mk_image(3))
        cli.merge(res.bootstrap, "a")
        digs = [c.digest for c in bc.dict.bootstrap.chunks[:4]]
        assert (cli.probe(digs, "a") >= 0).all()
        assert (cli.probe(digs, "b") == -1).all()
        names = {d["namespace"] for d in cli.namespaces()}
        assert {"a", "b"} <= names

    def test_invalid_namespace_rejected(self, service):
        cli = DictClient(service.sock_path)
        # ".." has no route match (404); an in-charset-but-invalid name
        # like a leading dot is caught by the namespace check (400).
        with pytest.raises(DictServiceError, match="404"):
            cli.stats("../escape")
        with pytest.raises(DictServiceError, match="400|invalid"):
            cli.stats(".hidden")

    def test_probe_body_must_be_digest_multiple(self, service):
        cli = DictClient(service.sock_path)
        with pytest.raises(DictServiceError, match="multiple of 32"):
            cli._request("POST", "/api/v1/dict/ns/probe", b"short")

    def test_save_writes_bootstrap_and_index(self, service, tmp_path):
        from nydus_snapshotter_tpu.models.bootstrap import ChunkDict
        from nydus_snapshotter_tpu.parallel.sharded_dict import ShardedChunkDict

        cli = DictClient(service.sock_path)
        bc = BatchConverter(OPT)
        res = bc.convert_image("img", mk_image(4))
        cli.merge(res.bootstrap, "ns")
        path = str(tmp_path / "dict.boot")
        out = cli.save(path, "ns")
        assert out["index_save"]["mode"] in ("append", "full")
        cd = ChunkDict.from_path(path)
        assert len(cd) == cli.stats("ns")["chunks"]
        idx = ShardedChunkDict.load(path + ".idx", probe_backend="host")
        digs = [c.digest for c in cd.bootstrap.chunks]
        assert np.array_equal(idx.lookup_digests(digs), np.arange(len(digs)))

    def test_rpc_chaos_surfaces_and_service_survives(self, service):
        cli = DictClient(service.sock_path)
        failpoint.inject("dict.rpc", "error(OSError:chaos)*1")
        with pytest.raises(DictServiceError, match="chaos"):
            cli.stats("ns")
        # the fault was one-shot; the service keeps serving
        assert cli.stats("ns")["chunks"] == 0


class TestServiceChunkDictMirror:
    def test_mirror_replays_service_tail(self, service):
        cli = DictClient(service.sock_path)
        bc = BatchConverter(OPT)
        res = bc.convert_image("img", mk_image(5))
        cli.merge(res.bootstrap, "ns")
        mirror = ServiceChunkDict(DictClient(service.sock_path), "ns")
        assert len(mirror) == len(bc.dict)
        for c in bc.dict.bootstrap.chunks:
            hit = mirror.get(c.digest)
            assert hit is not None
            assert mirror.blob_id_for(hit) == bc.dict.blob_id_for(
                bc.dict.get(c.digest)
            )
        # incremental: a second image lands server-side, sync picks it up
        res2 = bc.convert_image("img2", mk_image(6))
        cli.merge(res2.bootstrap, "ns")
        got = mirror.sync()
        assert got > 0
        assert len(mirror) == len(bc.dict)

    def test_two_converters_share_one_table(self, service):
        """Converter B dedups against chunks converter A merged — the
        registry-wide sharing the per-process dict can never give."""
        a = BatchConverter(OPT, dict_service=service.sock_path, namespace="shared")
        b = BatchConverter(OPT, dict_service=service.sock_path, namespace="shared")
        res_a = a.convert_image("a", mk_image(7))
        assert res_a.new_dict_chunks > 0
        b.dict.sync()
        res_b = b.convert_image("b", mk_image(7, files=6))  # same content pool
        # image b's chunks were already in the shared dict via a
        assert res_b.new_dict_chunks < res_a.new_dict_chunks
        assert len(a.dict) <= len(b.dict)


class TestBatchByteIdentity:
    def test_service_path_identical_to_private_dict_path(self, service):
        images = [(f"img{k}", mk_image(100 + k)) for k in range(6)]
        bc_local = BatchConverter(OPT)
        r_local = bc_local.convert_many(images)
        bc_svc = BatchConverter(OPT, dict_service=service.sock_path, namespace="bi")
        r_svc = bc_svc.convert_many(images)
        assert [r.bootstrap for r in r_local] == [r.bootstrap for r in r_svc]
        assert [r.blob_digests for r in r_local] == [r.blob_digests for r in r_svc]
        assert [r.new_dict_chunks for r in r_local] == [
            r.new_dict_chunks for r in r_svc
        ]
        assert len(bc_local.dict) == len(bc_svc.dict)
        # cross-image dedup really engaged
        assert any(r.new_dict_chunks == 0 or len(r.blob_digests) > 1 for r in r_svc[1:])

    def test_dict_path_plus_service_rejected(self, service, tmp_path):
        seed = str(tmp_path / "seed.boot")
        BatchConverter(OPT).save_dict(seed)
        with pytest.raises(ConvertError, match="service"):
            BatchConverter(OPT, dict_path=seed, dict_service=service.sock_path)


class TestTracePropagation:
    def test_convert_root_spans_the_rpc(self, service):
        bc = BatchConverter(OPT, dict_service=service.sock_path, namespace="tr")
        trace.reset()
        bc.convert_image("img", mk_image(9))
        spans = trace.snapshot_spans()
        root = next(s for s in spans if not s.parent_id and s.name == "convert")
        rpc = [s for s in spans if s.name.startswith("dict.rpc.")]
        assert rpc, "no service-side spans recorded"
        assert all(s.trace_id == root.trace_id for s in rpc)
        ops = {s.name for s in rpc}
        assert "dict.rpc.merge" in ops

    def test_untraced_caller_is_fine(self, service):
        cli = DictClient(service.sock_path)
        assert cli.stats("x")["chunks"] == 0  # no active span: headers absent


class TestSystemControllerMount:
    def test_dict_routes_on_system_socket(self, tmp_path):
        from nydus_snapshotter_tpu.system import SystemController

        svc = DictService()
        sock = str(tmp_path / "system.sock")
        ctl = SystemController(sock_path=sock, dict_service=svc)
        ctl.run()
        try:
            cli = DictClient(sock)
            bc = BatchConverter(OPT)
            res = bc.convert_image("img", mk_image(11))
            out = cli.merge(res.bootstrap, "sys")
            assert out["added"] > 0
            st = cli.stats("sys")
            assert st["chunks"] == out["chunks"]
            # the ops routes still answer on the same socket
            _ctype, payload = cli._request("GET", "/api/v1/daemons")
            assert json.loads(payload) == []
        finally:
            ctl.stop()

    def test_without_dict_service_routes_404(self, tmp_path):
        from nydus_snapshotter_tpu.system import SystemController

        sock = str(tmp_path / "system.sock")
        ctl = SystemController(sock_path=sock)
        ctl.run()
        try:
            cli = DictClient(sock)
            with pytest.raises(DictServiceError, match="404"):
                cli.stats("ns")
        finally:
            ctl.stop()


class TestConfigResolution:
    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("NTPU_DICT_LOAD_FACTOR", "0.5")
        monkeypatch.setenv("NTPU_DICT_HEADROOM", "4.0")
        monkeypatch.setenv("NTPU_DICT_SERVICE", "/tmp/x.sock")
        monkeypatch.setenv("NTPU_DICT_NAMESPACE", "team-a")
        cfg = resolve_dict_config()
        assert cfg.load_factor == 0.5
        assert cfg.headroom == 4.0
        assert cfg.service == "/tmp/x.sock"
        assert cfg.namespace == "team-a"

    def test_config_section_validation(self):
        from nydus_snapshotter_tpu.config.config import ConfigError, load_config

        with pytest.raises(ConfigError, match="load_factor"):
            load_config(overrides={"chunk_dict": {"load_factor": 1.5}})
        with pytest.raises(ConfigError, match="headroom"):
            load_config(overrides={"chunk_dict": {"headroom": 0.5}})
        cfg = load_config(
            overrides={"chunk_dict": {"service": "/run/dict.sock", "headroom": 3.0}}
        )
        assert cfg.chunk_dict.service == "/run/dict.sock"

    def test_service_dict_honors_headroom(self):
        from nydus_snapshotter_tpu.parallel.dict_service import DictRuntimeConfig

        sd = ServiceDict(
            "ns", DictRuntimeConfig(0.7, 3.0, "", "ns", "host")
        )
        assert sd.index.load_factor == 0.7
        assert sd.index.capacity_factor == 3.0


class TestOpenChunkDict:
    def test_service_scheme_connects_mirror(self, service):
        cli = DictClient(service.sock_path)
        bc = BatchConverter(OPT)
        res = bc.convert_image("img", mk_image(13))
        cli.merge(res.bootstrap, "pth")
        cd = open_chunk_dict(f"service://{service.sock_path}#pth")
        assert isinstance(cd, ServiceChunkDict)
        assert len(cd) == len(bc.dict)

    def test_pack_dedups_through_service_scheme(self, service):
        """opt.chunk_dict_path = service://… routes a plain Pack through
        the shared table: a layer of already-known content produces real
        foreign-blob references."""
        from nydus_snapshotter_tpu.converter.convert import (
            Pack,
            bootstrap_from_layer_blob,
        )

        cli = DictClient(service.sock_path)
        bc = BatchConverter(OPT)
        layers = mk_image(15)
        res = bc.convert_image("img", layers)
        cli.merge(res.bootstrap, "pk")
        opt = PackOption(
            chunk_size=0x10000,
            chunking="cdc",
            chunk_dict_path=f"service://{service.sock_path}#pk",
        )
        out = io.BytesIO()
        # layers[1] is the overlay winner (both layers share file names),
        # so its per-file chunks are exactly what the merged image — and
        # therefore the service dict — holds.
        pres = Pack(out, layers[1], opt)
        bs = bootstrap_from_layer_blob(out.getvalue())
        foreign = {
            bs.blobs[c.blob_index].blob_id
            for c in bs.chunks
            if bs.blobs[c.blob_index].blob_id != pres.blob_id
        }
        assert foreign, "no dedup hits through the service-backed dict"

    def test_file_path_still_loads(self, tmp_path):
        from nydus_snapshotter_tpu.models.bootstrap import ChunkDict

        bc = BatchConverter(OPT)
        bc.convert_image("img", mk_image(16))
        p = str(tmp_path / "d.boot")
        bc.save_dict(p)
        cd = open_chunk_dict(p)
        assert isinstance(cd, ChunkDict)
        assert len(cd) == len(bc.dict)


# ---------------------------------------------------------------------------
# Sharded service: namespace key-space split across N service processes
# ---------------------------------------------------------------------------


@pytest.fixture()
def shard_pool(tmp_path):
    """Factory: spin up N DictService processes on tmp UDS paths."""
    started = []

    def make(n: int):
        svcs = []
        for i in range(n):
            svc = DictService()
            svc.run(str(tmp_path / f"shard{len(started)}_{i}.sock"))
            svcs.append(svc)
        started.extend(svcs)
        return svcs

    yield make
    for svc in started:
        svc.stop()


class TestShardRouting:
    def test_shard_for_stable_and_order_insensitive_scores(self):
        from nydus_snapshotter_tpu.parallel.dict_service import shard_for

        addrs = [f"/run/s{i}.sock" for i in range(4)]
        digs = [bytes([i]) * 32 for i in range(64)]
        owners = [shard_for(d, addrs) for d in digs]
        assert owners == [shard_for(d, addrs) for d in digs]  # deterministic
        assert len(set(owners)) > 1  # actually spreads
        # single shard short-circuits
        assert all(shard_for(d, addrs[:1]) == 0 for d in digs)

    def test_partition_covers_every_digest_once(self):
        from nydus_snapshotter_tpu.parallel.dict_service import partition_digests

        addrs = [f"/run/s{i}.sock" for i in range(3)]
        digs = [bytes([i % 251]) * 32 for i in range(300)]
        parts = partition_digests(digs, addrs)
        seen = sorted(p for part in parts for p in part)
        assert seen == list(range(len(digs)))


class TestShardedServiceIdentity:
    @pytest.mark.parametrize("shards", [2, 4])
    def test_output_identical_to_private_and_single_service(
        self, shard_pool, shards
    ):
        """ISSUE 13 acceptance: sharded dict service output byte-identical
        to the single-service path at 1/2/4 shards (1 = the existing
        TestBatchByteIdentity pin)."""
        images = [(f"img{k}", mk_image(300 + k)) for k in range(6)]
        r_local = BatchConverter(OPT).convert_many(images)
        svcs = shard_pool(shards)
        addrs = ",".join(s.sock_path for s in svcs)
        bc = BatchConverter(OPT, dict_service=addrs, namespace="shrd")
        r_shard = bc.convert_many(images)
        assert [r.bootstrap for r in r_local] == [r.bootstrap for r in r_shard]
        assert [r.blob_digests for r in r_local] == [
            r.blob_digests for r in r_shard
        ]
        assert [r.new_dict_chunks for r in r_local] == [
            r.new_dict_chunks for r in r_shard
        ]
        assert bc.dict.n_shards == shards
        # the key-space actually split: more than one shard holds chunks
        per_shard = [e["chunks"] for e in bc.dict.shard_epochs()]
        assert sum(per_shard) == len(bc.dict)
        assert sum(1 for c in per_shard if c) > 1

    def test_two_sharded_converters_share_the_table(self, shard_pool):
        svcs = shard_pool(2)
        addrs = ",".join(s.sock_path for s in svcs)
        a = BatchConverter(OPT, dict_service=addrs, namespace="sh2")
        b = BatchConverter(OPT, dict_service=addrs, namespace="sh2")
        res_a = a.convert_image("a", mk_image(7))
        b.dict.sync()
        res_b = b.convert_image("b", mk_image(7, files=6))
        assert res_b.new_dict_chunks < res_a.new_dict_chunks

    def test_open_chunk_dict_multi_addr(self, shard_pool):
        svcs = shard_pool(2)
        addrs = ",".join(s.sock_path for s in svcs)
        cd = open_chunk_dict(f"service://{addrs}#multi")
        assert isinstance(cd, ServiceChunkDict)
        assert cd.n_shards == 2


class TestShardedEpochReconciliation:
    def test_entries_since_tail_and_count_only(self, service):
        from nydus_snapshotter_tpu.parallel.sharded_dict import DictEpochError

        cli = DictClient(service.sock_path)
        bc = BatchConverter(OPT)
        res = bc.convert_image("img", mk_image(21))
        cli.merge(res.bootstrap, "since")
        meta, digs, vals = cli.entries_since("since", epoch=0)
        assert meta["entries"] == len(bc.dict) == len(vals)
        assert digs.shape == (len(vals), 8)
        meta2, d2, v2 = cli.entries_since("since", epoch=0, count_only=True)
        assert meta2["entries"] == meta["entries"]
        assert len(d2) == len(v2) == 0
        # caught-up caller gets an empty tail at the current epoch
        meta3, d3, _v3 = cli.entries_since("since", epoch=meta["epoch"])
        assert meta3["entries"] == 0 and meta3["epoch"] == meta["epoch"]
        assert isinstance(DictEpochError("x"), RuntimeError)

    def test_compacted_journal_is_a_409_epoch_error(self, service):
        from nydus_snapshotter_tpu.parallel.sharded_dict import DictEpochError

        cli = DictClient(service.sock_path)
        sd = service.dict_for("cmp")
        bc = BatchConverter(OPT)
        cli.merge(bc.convert_image("img", mk_image(22)).bootstrap, "cmp")
        # Force a rebuild/compaction: the journal before it is gone.
        with sd._mu:
            sd.index._rebuild()
        with pytest.raises(DictEpochError):
            cli.entries_since("cmp", epoch=0)

    def test_shard_restart_detected_loudly(self, tmp_path):
        """A shard that restarts with a younger table must not silently
        resume the record tail mid-stream: sync raises DictEpochError."""
        from nydus_snapshotter_tpu.parallel.sharded_dict import DictEpochError

        sock = str(tmp_path / "restart.sock")
        svc = DictService()
        svc.run(sock)
        try:
            bc = BatchConverter(OPT, dict_service=sock, namespace="rst")
            bc.convert_image("img", mk_image(23))
            assert bc.dict._shards[0].epoch > 0
            svc.stop()
            svc = DictService()  # fresh, empty table on the same address
            svc.run(sock)
            bc.dict.client.close()
            with pytest.raises(DictEpochError, match="backwards"):
                bc.dict.sync()
        finally:
            svc.stop()


class TestShardChaos:
    def test_dict_shard_failpoint_fails_merge_loudly(self, shard_pool):
        svcs = shard_pool(2)
        addrs = ",".join(s.sock_path for s in svcs)
        bc = BatchConverter(OPT, dict_service=addrs, namespace="chaos")
        failpoint.inject("dict.shard", "error(OSError:shard-chaos)*1")
        with pytest.raises(OSError, match="shard-chaos"):
            bc.convert_image("img", mk_image(25))
        failpoint.clear("dict.shard")
        # one-shot fault: the next image converts and dedups normally
        res = bc.convert_image("img", mk_image(25))
        assert res.new_dict_chunks > 0

    def test_dead_shard_surfaces_not_corrupts(self, shard_pool, tmp_path):
        svcs = shard_pool(2)
        addrs = ",".join(s.sock_path for s in svcs)
        bc = BatchConverter(OPT, dict_service=addrs, namespace="dead")
        bc.convert_image("img", mk_image(26))
        svcs[1].stop()
        for sh in bc.dict._shards:
            # a crashed process drops its connections; ThreadingHTTPServer
            # shutdown alone leaves kept-alive handler threads serving
            sh.client.close()
        with pytest.raises((DictServiceError, OSError)):
            for k in range(8):  # enough images that shard 1 owns something
                bc.convert_image(f"img{k}", mk_image(400 + k))
