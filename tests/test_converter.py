"""Converter tests, modeled on the reference smoke suite.

Mirrors tests/converter_test.go: synthetic OCI layer tars built in memory
(buildOCILowerTar/buildOCIUpperTar :177-225), pack v5+v6, merge with a chunk
dict, assert the returned blob-digest list equals the dedup expectation
(:515-521), and verify the file tree byte-for-byte after unpack (:380-418 —
the reference walks the FUSE mount; we walk the unpacked tar).
"""

import hashlib
import io
import tarfile

import numpy as np
import pytest

from nydus_snapshotter_tpu.converter import Merge, MergeOption, Pack, PackOption, UnpackOption, Unpack, pack_layer
from nydus_snapshotter_tpu.converter.convert import (
    blob_data_from_layer_blob,
    bootstrap_from_layer_blob,
)
from nydus_snapshotter_tpu.converter.types import ConvertError
from nydus_snapshotter_tpu.models import fstree, layout
from nydus_snapshotter_tpu.models.bootstrap import Bootstrap

RNG = np.random.default_rng(99)


def _rand(n: int) -> bytes:
    # hugeString analog (converter_test.go:91): pseudo-random, reproducible
    return RNG.integers(0, 256, n, dtype=np.uint8).tobytes()


def build_tar(files: list[tuple], dirs=(), symlinks=(), hardlinks=(), whiteouts=(), opaques=()) -> bytes:
    """In-memory OCI layer tar (buildOCILowerTar analog)."""
    out = io.BytesIO()
    with tarfile.open(fileobj=out, mode="w:") as tf:
        for d in dirs:
            info = tarfile.TarInfo(d.strip("/") + "/")
            info.type = tarfile.DIRTYPE
            info.mode = 0o755
            tf.addfile(info)
        for name, data in files:
            info = tarfile.TarInfo(name.strip("/"))
            info.size = len(data)
            info.mode = 0o644
            tf.addfile(info, io.BytesIO(data))
        for name, target in symlinks:
            info = tarfile.TarInfo(name.strip("/"))
            info.type = tarfile.SYMTYPE
            info.linkname = target
            tf.addfile(info)
        for name, target in hardlinks:
            info = tarfile.TarInfo(name.strip("/"))
            info.type = tarfile.LNKTYPE
            info.linkname = target.strip("/")
            tf.addfile(info)
        for name in whiteouts:
            parent, _, base = name.strip("/").rpartition("/")
            info = tarfile.TarInfo((parent + "/" if parent else "") + ".wh." + base)
            tf.addfile(info)
        for d in opaques:
            info = tarfile.TarInfo(d.strip("/") + "/.wh..wh..opq")
            tf.addfile(info)
    return out.getvalue()


def tar_tree(tar_bytes: bytes) -> dict:
    """path -> (type, payload) map for tree comparison."""
    out = {}
    with tarfile.open(fileobj=io.BytesIO(tar_bytes), mode="r:") as tf:
        for info in tf:
            name = "/" + info.name.strip("/")
            if info.isreg():
                out[name] = ("reg", tf.extractfile(info).read(), info.mode)
            elif info.issym():
                out[name] = ("sym", info.linkname)
            elif info.islnk():
                out[name] = ("lnk", "/" + info.linkname.strip("/"))
            elif info.isdir():
                out[name] = ("dir",)
            else:
                out[name] = (info.type,)
    return out


LOWER_FILES = [
    ("dir-1/file-2", _rand(20_000)),
    ("dir-2/file-1", b"lower-file-1-content" * 500),
    ("dir-2/file-3", _rand(5_000)),
]


def build_lower() -> bytes:
    return build_tar(
        LOWER_FILES,
        dirs=["dir-1", "dir-2"],
        symlinks=[("dir-2/link-1", "../dir-1/file-2")],
        hardlinks=[("dir-2/hard-1", "dir-2/file-1")],
    )


def build_upper() -> bytes:
    return build_tar(
        [("dir-2/file-1", b"upper-overrides" * 300), ("dir-3/file-4", _rand(8_000))],
        dirs=["dir-2", "dir-3"],
        whiteouts=["dir-2/file-3"],
    )


@pytest.fixture(scope="module", params=["v5", "v6"])
def fs_version(request):
    return request.param


@pytest.fixture(scope="module")
def opt(fs_version):
    return PackOption(fs_version=fs_version, chunk_size=0x1000, backend="jax")


class TestPackUnpack:
    def test_single_layer_roundtrip(self, opt):
        src = build_lower()
        blob, res = pack_layer(src, opt)
        assert res.blob_id and res.blob_size > 0
        bs = bootstrap_from_layer_blob(blob)
        assert bs.version == opt.fs_version
        out_tar = Unpack(bs, {res.blob_id: blob_data_from_layer_blob(blob)})
        src_tree, out_tree = tar_tree(src), tar_tree(out_tar)
        for path, val in src_tree.items():
            assert out_tree[path][:2] == val[:2], path
        assert out_tree["/dir-2/hard-1"] == ("lnk", "/dir-2/file-1")

    def test_pack_deterministic(self, opt):
        src = build_lower()
        a, _ = pack_layer(src, opt)
        b, _ = pack_layer(src, opt)
        assert a == b

    def test_compression_shrinks_blob(self):
        src = build_tar([("a/compressible", b"A" * 500_000)], dirs=["a"])
        blob, res = pack_layer(src, PackOption(chunk_size=0x1000))
        assert res.blob_size < 50_000

    def test_compressor_none(self):
        src = build_lower()
        blob, res = pack_layer(src, PackOption(compressor="none", chunk_size=0x1000))
        bs = bootstrap_from_layer_blob(blob)
        out_tar = Unpack(bs, {res.blob_id: blob_data_from_layer_blob(blob)})
        assert tar_tree(out_tar)["/dir-1/file-2"][1] == LOWER_FILES[0][1]

    def test_intra_layer_dedup(self):
        # Two identical large files: blob stores the data once.
        data = _rand(300_000)
        src = build_tar([("x/a", data), ("x/b", data)], dirs=["x"])
        _, res = pack_layer(src, PackOption(compressor="none", chunk_size=0x1000))
        assert res.blob_size < 320_000

    def test_invalid_options(self):
        with pytest.raises(ConvertError):
            pack_layer(build_lower(), PackOption(chunk_size=0x1800))
        with pytest.raises(ConvertError):
            pack_layer(build_lower(), PackOption(fs_version="v7"))
        with pytest.raises(ConvertError):
            pack_layer(build_lower(), PackOption(compressor="brotli"))


class TestMerge:
    def test_overlay_merge_and_unpack(self, opt):
        lower_blob, lres = pack_layer(build_lower(), opt)
        upper_blob, ures = pack_layer(build_upper(), opt)
        merged = Merge([lower_blob, upper_blob], MergeOption(fs_version=opt.fs_version))
        bs = Bootstrap.from_bytes(merged.bootstrap)
        blobs = {
            lres.blob_id: blob_data_from_layer_blob(lower_blob),
            ures.blob_id: blob_data_from_layer_blob(upper_blob),
        }
        out_tree = tar_tree(Unpack(bs, blobs))
        assert out_tree["/dir-2/file-1"][1] == b"upper-overrides" * 300  # upper wins
        assert "/dir-2/file-3" not in out_tree  # whiteout applied
        assert out_tree["/dir-3/file-4"][0] == "reg"
        assert out_tree["/dir-1/file-2"][1] == LOWER_FILES[0][1]  # lower survives
        assert set(merged.blob_digests) == {lres.blob_id, ures.blob_id}

    def test_merge_with_chunk_dict_dedup(self, tmp_path, opt):
        # Chunk-dict dedup expectation (converter_test.go:515-521): a layer
        # whose data is already in the dict image must not contribute its
        # blob to the merged blob list.
        shared = _rand(400_000)
        # The dict image carries extra content so its blob id differs from a
        # blob packed from `shared` alone (blob ids hash chunk data only).
        dict_blob, dict_res = pack_layer(
            build_tar([("d/shared", shared), ("d/extra", _rand(30_000))], dirs=["d"]), opt
        )
        dict_merged = Merge([dict_blob], MergeOption(fs_version=opt.fs_version))
        dict_path = tmp_path / "dict.boot"
        dict_path.write_bytes(dict_merged.bootstrap)

        # New image: one layer fully covered by the dict, one layer new.
        dup_blob, dup_res = pack_layer(
            build_tar([("img/copy", shared)], dirs=["img"]), opt
        )
        new_blob, new_res = pack_layer(
            build_tar([("img/new", _rand(50_000))], dirs=["img"]), opt
        )
        merged = Merge(
            [dup_blob, new_blob],
            MergeOption(fs_version=opt.fs_version, chunk_dict_path=str(dict_path)),
        )
        # Dedup: the duplicate layer's blob is fully replaced by the dict blob.
        assert dict_res.blob_id in merged.blob_digests
        assert dup_res.blob_id not in merged.blob_digests
        assert new_res.blob_id in merged.blob_digests

        # And the merged image still unpacks byte-exactly, reading shared
        # data from the dict blob.
        bs = Bootstrap.from_bytes(merged.bootstrap)
        blobs = {
            dict_res.blob_id: blob_data_from_layer_blob(dict_blob),
            new_res.blob_id: blob_data_from_layer_blob(new_blob),
        }
        out_tree = tar_tree(Unpack(bs, blobs))
        assert out_tree["/img/copy"][1] == shared

    def test_pack_with_chunk_dict(self, tmp_path, opt):
        # Pack-time dedup (reference `create --chunk-dict`): chunks already
        # in the dict are not stored in the new blob.
        shared = _rand(400_000)
        dict_blob, dict_res = pack_layer(
            build_tar([("d/s", shared), ("d/other", _rand(20_000))], dirs=["d"]), opt
        )
        dict_path = tmp_path / "dict.boot"
        dict_path.write_bytes(Merge([dict_blob], MergeOption()).bootstrap)

        opt2 = PackOption(
            fs_version=opt.fs_version,
            chunk_size=0x1000,
            chunk_dict_path=str(dict_path),
        )
        blob, res = pack_layer(
            build_tar([("i/dup", shared), ("i/tiny", b"small new data")], dirs=["i"]), opt2
        )
        assert res.blob_size < 10_000  # shared content not re-stored
        assert dict_res.blob_id in res.referenced_blob_ids
        bs = bootstrap_from_layer_blob(blob)
        out_tree = tar_tree(
            Unpack(
                bs,
                {
                    res.blob_id: blob_data_from_layer_blob(blob),
                    dict_res.blob_id: blob_data_from_layer_blob(dict_blob),
                },
            )
        )
        assert out_tree["/i/dup"][1] == shared
        assert out_tree["/i/tiny"][1] == b"small new data"

    def test_opaque_dir(self, opt):
        lower = build_tar([("od/keep", b"low")], dirs=["od"])
        upper = build_tar([("od/newf", b"up")], dirs=["od"], opaques=["od"])
        lb, lres = pack_layer(lower, opt)
        ub, ures = pack_layer(upper, opt)
        merged = Merge([lb, ub], MergeOption())
        bs = Bootstrap.from_bytes(merged.bootstrap)
        tree = tar_tree(
            Unpack(bs, {lres.blob_id: blob_data_from_layer_blob(lb),
                        ures.blob_id: blob_data_from_layer_blob(ub)})
        )
        assert "/od/keep" not in tree
        assert tree["/od/newf"][1] == b"up"

    def test_merge_empty_layers_rejected(self):
        with pytest.raises(ConvertError):
            Merge([], MergeOption())

    def test_merge_inherits_layer_version(self):
        blob, _ = pack_layer(build_lower(), PackOption(fs_version="v5", chunk_size=0x1000))
        merged = Merge([blob], MergeOption())
        assert Bootstrap.from_bytes(merged.bootstrap).version == "v5"
        merged6 = Merge([blob], MergeOption(fs_version="v6"))
        assert Bootstrap.from_bytes(merged6.bootstrap).version == "v6"


class TestFsTreeFidelity:
    def test_binary_xattr_roundtrip(self):
        # security.capability-style binary xattr survives pack->unpack.
        cap = b"\x01\x00\x00\x02\xff\x00\xde\xad"
        out = io.BytesIO()
        with tarfile.open(fileobj=out, mode="w:", format=tarfile.PAX_FORMAT) as tf:
            info = tarfile.TarInfo("bin/ping")
            info.size = 4
            info.pax_headers["SCHILY.xattr.security.capability"] = cap.decode(
                "utf-8", "surrogateescape"
            )
            tf.addfile(info, io.BytesIO(b"ELF!"))
        blob, res = pack_layer(out.getvalue(), PackOption(chunk_size=0x1000))
        bs = bootstrap_from_layer_blob(blob)
        assert bs.inode_by_path()["/bin/ping"].xattrs["security.capability"] == cap
        out_tar = Unpack(bs, {res.blob_id: blob_data_from_layer_blob(blob)})
        with tarfile.open(fileobj=io.BytesIO(out_tar), mode="r:") as tf:
            v = tf.getmember("bin/ping").pax_headers["SCHILY.xattr.security.capability"]
            assert v.encode("utf-8", "surrogateescape") == cap

    def test_large_device_minor_roundtrip(self):
        out = io.BytesIO()
        with tarfile.open(fileobj=out, mode="w:") as tf:
            info = tarfile.TarInfo("dev/dm-0")
            info.type = tarfile.BLKTYPE
            info.devmajor, info.devminor = 253, 300  # minor > 255
            tf.addfile(info)
        blob, res = pack_layer(out.getvalue(), PackOption(chunk_size=0x1000))
        out_tar = Unpack(
            bootstrap_from_layer_blob(blob),
            {res.blob_id: blob_data_from_layer_blob(blob)},
        )
        with tarfile.open(fileobj=io.BytesIO(out_tar), mode="r:") as tf:
            m = tf.getmember("dev/dm-0")
            assert (m.devmajor, m.devminor) == (253, 300)
