"""EROFS image writer: the kernel is the format oracle.

The produced image is loop-attached and mounted with the in-kernel erofs
driver (the reference's blockdev path, pkg/utils/erofs/erofs.go:18-47 +
pkg/tarfs loop attach :754), then walked byte-for-byte. Pure-python
structural assertions run everywhere; the mount tests skip where loop
devices / mount(2) are unavailable.
"""

import ctypes
import os
import stat as statmod
import struct
import subprocess
import tempfile

import numpy as np
import pytest

from nydus_snapshotter_tpu.models.erofs_image import (
    BLKSZ,
    EROFS_MAGIC,
    ErofsError,
    build_erofs,
)
from nydus_snapshotter_tpu.models.fstree import FileEntry

RNG = np.random.default_rng(0xE20F5)


def entry(path, mode=0o644, data=b"", **kw):
    return FileEntry(path=path, mode=mode, data=data, **kw)


def sample_entries():
    big = RNG.integers(0, 256, 150_000, dtype=np.uint8).tobytes()
    return [
        entry("/etc", statmod.S_IFDIR | 0o755),
        entry("/etc/hosts", statmod.S_IFREG | 0o644, b"127.0.0.1 localhost\n"),
        entry("/etc/empty", statmod.S_IFREG | 0o600, b""),
        entry("/bin", statmod.S_IFDIR | 0o755),
        entry("/bin/app", statmod.S_IFREG | 0o755, big),
        entry("/bin/link", statmod.S_IFLNK | 0o777, symlink_target="app"),
        entry("/bin/hard", statmod.S_IFREG | 0o755, hardlink_target="/bin/app"),
        entry("/deep", statmod.S_IFDIR | 0o755),
        entry("/deep/a", statmod.S_IFDIR | 0o755),
        entry("/deep/a/b", statmod.S_IFDIR | 0o755),
        entry("/deep/a/b/leaf", statmod.S_IFREG | 0o644, b"leaf-data"),
    ], big


class TestStructure:
    def test_superblock_fields(self):
        entries, _ = sample_entries()
        img = build_erofs(entries)
        assert len(img) % BLKSZ == 0
        magic, _cs, _fc, blkszbits = struct.unpack_from("<IIIB", img, 1024)
        assert magic == EROFS_MAGIC
        assert blkszbits == 12
        # pkg/layout's v6 detection must recognize it
        from nydus_snapshotter_tpu.models import layout

        assert layout.detect_fs_version(img) == layout.RAFS_V6

    def test_many_files_multiblock_dir(self):
        entries = [entry("/d", statmod.S_IFDIR | 0o755)] + [
            entry(f"/d/file-{i:04d}", statmod.S_IFREG | 0o644, bytes([i % 256]) * 10)
            for i in range(600)  # > one 4K dirent block
        ]
        img = build_erofs(entries)
        assert len(img) % BLKSZ == 0

    def test_hardlink_to_missing_target_rejected(self):
        with pytest.raises(ErofsError):
            build_erofs([entry("/x", statmod.S_IFREG | 0o644, hardlink_target="/gone")])

    def test_long_name_rejected(self):
        with pytest.raises(ErofsError):
            build_erofs([entry("/" + "n" * 300, statmod.S_IFREG | 0o644)])


def _mount_available() -> bool:
    if os.geteuid() != 0 or not os.path.exists("/dev/loop-control"):
        return False
    try:
        with open("/proc/filesystems") as f:
            return "\terofs" in f.read()
    except OSError:
        return False


requires_erofs = pytest.mark.skipif(
    not _mount_available(), reason="need root + loop devices + erofs kernel driver"
)


class _Mounted:
    """losetup + mount -t erofs via util-linux (what the reference shells
    into through pkg/tarfs attachLoopdev + erofs.Mount)."""

    def __init__(self, image_path: str, mountpoint: str):
        self.image_path = image_path
        self.mountpoint = mountpoint
        self.loop = None

    def __enter__(self):
        out = subprocess.run(
            ["losetup", "--find", "--show", "--read-only", self.image_path],
            capture_output=True, text=True, check=True,
        )
        self.loop = out.stdout.strip()
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        rc = libc.mount(
            self.loop.encode(), self.mountpoint.encode(), b"erofs", 1, b""
        )
        if rc != 0:
            err = os.strerror(ctypes.get_errno())
            subprocess.run(["losetup", "-d", self.loop], check=False)
            raise RuntimeError(f"mount -t erofs failed: {err}")
        return self

    def __exit__(self, *exc):
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        libc.umount2(self.mountpoint.encode(), 2)
        if self.loop:
            subprocess.run(["losetup", "-d", self.loop], check=False)


@requires_erofs
class TestKernelMount:
    def test_mount_walk_byte_for_byte(self, tmp_path):
        entries, big = sample_entries()
        img = build_erofs(entries)
        image_path = str(tmp_path / "img.erofs")
        with open(image_path, "wb") as f:
            f.write(img)
        mp = str(tmp_path / "mnt")
        os.mkdir(mp)
        with _Mounted(image_path, mp):
            with open(os.path.join(mp, "etc/hosts"), "rb") as f:
                assert f.read() == b"127.0.0.1 localhost\n"
            with open(os.path.join(mp, "bin/app"), "rb") as f:
                assert f.read() == big
            with open(os.path.join(mp, "bin/app"), "rb") as f:
                f.seek(70_000)
                assert f.read(100) == big[70_000:70_100]
            assert os.readlink(os.path.join(mp, "bin/link")) == "app"
            with open(os.path.join(mp, "bin/hard"), "rb") as f:
                assert f.read() == big
            st = os.stat(os.path.join(mp, "bin/app"))
            assert st.st_nlink == 2  # hardlink counted
            assert st.st_mode & 0o777 == 0o755
            assert os.stat(os.path.join(mp, "etc/empty")).st_size == 0
            with open(os.path.join(mp, "deep/a/b/leaf"), "rb") as f:
                assert f.read() == b"leaf-data"
            assert sorted(os.listdir(os.path.join(mp, "bin"))) == [
                "app", "hard", "link",
            ]
            assert sorted(os.listdir(mp)) == ["bin", "deep", "etc"]

    def test_mount_600_entry_directory(self, tmp_path):
        n = 600
        entries = [entry("/d", statmod.S_IFDIR | 0o755)] + [
            entry(f"/d/file-{i:04d}", statmod.S_IFREG | 0o644, b"%d" % i)
            for i in range(n)
        ]
        img = build_erofs(entries)
        image_path = str(tmp_path / "big.erofs")
        with open(image_path, "wb") as f:
            f.write(img)
        mp = str(tmp_path / "mnt")
        os.mkdir(mp)
        with _Mounted(image_path, mp):
            names = os.listdir(os.path.join(mp, "d"))
            assert len(names) == n
            # lookups hit the kernel's binary search across dirent blocks
            for i in (0, 1, 299, 300, 598, 599):
                with open(os.path.join(mp, "d", f"file-{i:04d}"), "rb") as f:
                    assert f.read() == b"%d" % i
            assert not os.path.exists(os.path.join(mp, "d", "file-9999"))

    def test_converted_layer_to_erofs_mount(self, tmp_path):
        """OCI tar -> pack -> unpack tree -> EROFS image -> kernel mount:
        the blockdev-mode endgame without any external builder."""
        import io
        import tarfile

        from nydus_snapshotter_tpu.converter.convert import (
            blob_data_from_layer_blob,
            bootstrap_from_layer_blob,
            make_bytes_reader,
            pack_layer,
        )
        from nydus_snapshotter_tpu.converter.types import PackOption

        payload = RNG.integers(0, 256, 90_000, dtype=np.uint8).tobytes()
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tf:
            ti = tarfile.TarInfo("app")
            ti.type = tarfile.DIRTYPE
            tf.addfile(ti)
            ti = tarfile.TarInfo("app/data.bin")
            ti.size = len(payload)
            tf.addfile(ti, io.BytesIO(payload))
        blob, res = pack_layer(buf.getvalue(), PackOption(chunk_size=0x1000))
        bs = bootstrap_from_layer_blob(blob)
        reader = make_bytes_reader(bs, 0, blob_data_from_layer_blob(blob))

        from nydus_snapshotter_tpu.models import fstree

        entries = []
        for inode in bs.inodes:
            data = b""
            if statmod.S_ISREG(inode.mode) and inode.chunk_count and not inode.hardlink_target:
                data = b"".join(
                    reader.chunk_data(c)
                    for c in bs.chunks[
                        inode.chunk_index : inode.chunk_index + inode.chunk_count
                    ]
                )
            entries.append(fstree.inode_to_entry(inode, data))
        img = build_erofs(entries)
        image_path = str(tmp_path / "layer.erofs")
        with open(image_path, "wb") as f:
            f.write(img)
        mp = str(tmp_path / "mnt")
        os.mkdir(mp)
        with _Mounted(image_path, mp):
            with open(os.path.join(mp, "app/data.bin"), "rb") as f:
                assert f.read() == payload


class TestHardlinkChains:
    def test_chained_hardlink_resolves_to_real_inode(self):
        entries = [
            entry("/c", statmod.S_IFREG | 0o644, b"real-data"),
            entry("/b", statmod.S_IFREG | 0o644, hardlink_target="/c"),
            entry("/a", statmod.S_IFREG | 0o644, hardlink_target="/b"),
        ]
        img = build_erofs(entries)  # must not point /a at nid 0
        if _mount_available():
            with tempfile.TemporaryDirectory() as d:
                image_path = os.path.join(d, "img")
                with open(image_path, "wb") as f:
                    f.write(img)
                mp = os.path.join(d, "mnt")
                os.mkdir(mp)
                with _Mounted(image_path, mp):
                    for name in ("a", "b", "c"):
                        with open(os.path.join(mp, name), "rb") as f:
                            assert f.read() == b"real-data", name
                    assert os.stat(os.path.join(mp, "c")).st_nlink == 3

    def test_hardlink_cycle_rejected(self):
        entries = [
            entry("/a", statmod.S_IFREG | 0o644, hardlink_target="/b"),
            entry("/b", statmod.S_IFREG | 0o644, hardlink_target="/a"),
        ]
        with pytest.raises(ErofsError):
            build_erofs(entries)

    def test_oversized_metadata_rejected(self):
        with pytest.raises(ErofsError):
            build_erofs([entry("/u", statmod.S_IFREG | 0o644, uid=70_000)])


class _MountedWithDevice(_Mounted):
    """mount -t erofs -o device=<blob loop> (the reference's tarfs mount,
    tarfs.go:573-662: bootstrap disk as primary, tar blobs as devices)."""

    def __init__(self, image_path, blob_path, mountpoint):
        super().__init__(image_path, mountpoint)
        self.blob_path = blob_path
        self.blob_loop = None

    def __enter__(self):
        out = subprocess.run(
            ["losetup", "--find", "--show", "--read-only", self.image_path],
            capture_output=True, text=True, check=True,
        )
        self.loop = out.stdout.strip()
        out = subprocess.run(
            ["losetup", "--find", "--show", "--read-only", self.blob_path],
            capture_output=True, text=True, check=True,
        )
        self.blob_loop = out.stdout.strip()
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        rc = libc.mount(
            self.loop.encode(), self.mountpoint.encode(), b"erofs", 1,
            f"device={self.blob_loop}".encode(),
        )
        if rc != 0:
            err = os.strerror(ctypes.get_errno())
            for lo in (self.loop, self.blob_loop):
                subprocess.run(["losetup", "-d", lo], check=False)
            raise RuntimeError(f"mount -t erofs -o device= failed: {err}")
        return self

    def __exit__(self, *exc):
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        libc.umount2(self.mountpoint.encode(), 2)
        for lo in (self.loop, self.blob_loop):
            if lo:
                subprocess.run(["losetup", "-d", lo], check=False)


@requires_erofs
class TestChunkBasedTarfs:
    def test_tar_is_the_data_plane(self, tmp_path):
        """tarfs endgame: the uncompressed tar loop-attached as the blob
        device, an EROFS meta image whose chunk indexes point into it, the
        kernel reading file bytes straight from the tar."""
        import io
        import tarfile

        from nydus_snapshotter_tpu.models.erofs_image import ChunkedData

        big = RNG.integers(0, 256, 10_000_000, dtype=np.uint8).tobytes()  # ~9.5 MiB
        small = b"tarfs says hi\n"
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w", format=tarfile.GNU_FORMAT) as tf:
            ti = tarfile.TarInfo("app")
            ti.type = tarfile.DIRTYPE
            tf.addfile(ti)
            ti = tarfile.TarInfo("app/big.bin")
            ti.size = len(big)
            tf.addfile(ti, io.BytesIO(big))
            ti = tarfile.TarInfo("app/small.txt")
            ti.size = len(small)
            tf.addfile(ti, io.BytesIO(small))
        tar_bytes = buf.getvalue()

        # Locate each member's data offset inside the tar (what
        # tarfs/bootstrap.py records as chunk offsets).
        offs = {}
        with tarfile.open(fileobj=io.BytesIO(tar_bytes)) as tf:
            for m in tf.getmembers():
                if m.isreg():
                    offs[m.name] = (m.offset_data, m.size)

        CHUNK = 1 << 20  # 1 MiB chunks
        chunk_map = {}
        for name, (off, size) in offs.items():
            offsets = [off + k * CHUNK for k in range(-(-size // CHUNK))]
            chunk_map["/" + name] = ChunkedData(size=size, chunk_size=CHUNK, offsets=offsets)

        entries = [
            entry("/app", statmod.S_IFDIR | 0o755),
            entry("/app/big.bin", statmod.S_IFREG | 0o644),
            entry("/app/small.txt", statmod.S_IFREG | 0o644),
        ]
        img = build_erofs(
            entries,
            blkszbits=9,  # tar data is 512-aligned
            chunk_map=chunk_map,
            device=(b"layer-tar", len(tar_bytes)),
        )
        image_path = str(tmp_path / "meta.erofs")
        blob_path = str(tmp_path / "layer.tar")
        with open(image_path, "wb") as f:
            f.write(img)
        with open(blob_path, "wb") as f:
            f.write(tar_bytes)
            f.write(b"\0" * (-len(tar_bytes) % 512))
        mp = str(tmp_path / "mnt")
        os.mkdir(mp)
        with _MountedWithDevice(image_path, blob_path, mp):
            with open(os.path.join(mp, "app/small.txt"), "rb") as f:
                assert f.read() == small
            with open(os.path.join(mp, "app/big.bin"), "rb") as f:
                assert f.read() == big
            # ranged read across a chunk boundary
            with open(os.path.join(mp, "app/big.bin"), "rb") as f:
                f.seek(CHUNK - 100)
                assert f.read(200) == big[CHUNK - 100 : CHUNK + 100]

    def test_chunk_offsets_must_be_aligned(self):
        from nydus_snapshotter_tpu.models.erofs_image import ChunkedData

        with pytest.raises(ErofsError):
            build_erofs(
                [entry("/f", statmod.S_IFREG | 0o644)],
                blkszbits=9,
                chunk_map={"/f": ChunkedData(size=10, chunk_size=512, offsets=[100])},
                device=(b"t", 4096),
            )

    def test_chunk_map_requires_device(self):
        from nydus_snapshotter_tpu.models.erofs_image import ChunkedData

        with pytest.raises(ErofsError):
            build_erofs(
                [entry("/f", statmod.S_IFREG | 0o644)],
                chunk_map={"/f": ChunkedData(size=10, chunk_size=4096, offsets=[0])},
            )


@requires_erofs
class TestTarfsBootstrapExport:
    def test_tarfs_bootstrap_to_kernel_mount(self, tmp_path):
        """tarfs pipeline end-to-end: index the tar (tarfs/bootstrap.py),
        export the bootstrap to a real EROFS meta image, kernel-mount with
        the tar as the blob device, walk byte-for-byte."""
        import io
        import tarfile

        from nydus_snapshotter_tpu.models.erofs_image import erofs_from_rafs
        from nydus_snapshotter_tpu.tarfs.bootstrap import tarfs_bootstrap_from_tar

        payload = RNG.integers(0, 256, 5_000_000, dtype=np.uint8).tobytes()
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w", format=tarfile.GNU_FORMAT) as tf:
            for d in ("usr", "usr/lib"):
                ti = tarfile.TarInfo(d)
                ti.type = tarfile.DIRTYPE
                ti.mode = 0o755
                tf.addfile(ti)
            ti = tarfile.TarInfo("usr/lib/libbig.so")
            ti.size = len(payload)
            tf.addfile(ti, io.BytesIO(payload))
            ti = tarfile.TarInfo("usr/hello")
            ti.size = 12
            tf.addfile(ti, io.BytesIO(b"tarfs-hello\n"))
            ti = tarfile.TarInfo("usr/ln")
            ti.type = tarfile.SYMTYPE
            ti.linkname = "hello"
            tf.addfile(ti)
        tar_bytes = buf.getvalue()

        bs = tarfs_bootstrap_from_tar(io.BytesIO(tar_bytes), blob_id="tarblob")
        img = erofs_from_rafs(bs)

        image_path = str(tmp_path / "meta.erofs")
        blob_path = str(tmp_path / "layer.tar")
        with open(image_path, "wb") as f:
            f.write(img)
        with open(blob_path, "wb") as f:
            f.write(tar_bytes)
            f.write(b"\0" * (-len(tar_bytes) % 512))
        mp = str(tmp_path / "mnt")
        os.mkdir(mp)
        with _MountedWithDevice(image_path, blob_path, mp):
            with open(os.path.join(mp, "usr/lib/libbig.so"), "rb") as f:
                assert f.read() == payload
            with open(os.path.join(mp, "usr/hello"), "rb") as f:
                assert f.read() == b"tarfs-hello\n"
            assert os.readlink(os.path.join(mp, "usr/ln")) == "hello"


@requires_erofs
class TestXattrs:
    def test_xattrs_visible_through_kernel(self, tmp_path):
        entries = [
            entry("/opq", statmod.S_IFDIR | 0o755,
                  xattrs={"trusted.overlay.opaque": b"y"}),
            entry("/opq/f", statmod.S_IFREG | 0o644, b"inside"),
            entry("/tagged", statmod.S_IFREG | 0o644, b"data",
                  xattrs={"user.color": b"blue", "user.size": b"xl"}),
        ]
        img = build_erofs(entries)
        image_path = str(tmp_path / "x.erofs")
        with open(image_path, "wb") as f:
            f.write(img)
        mp = str(tmp_path / "mnt")
        os.mkdir(mp)
        with _Mounted(image_path, mp):
            assert os.getxattr(os.path.join(mp, "opq"), "trusted.overlay.opaque") == b"y"
            assert os.getxattr(os.path.join(mp, "tagged"), "user.color") == b"blue"
            assert os.getxattr(os.path.join(mp, "tagged"), "user.size") == b"xl"
            assert sorted(os.listxattr(os.path.join(mp, "tagged"))) == [
                "user.color", "user.size",
            ]
            with open(os.path.join(mp, "opq/f"), "rb") as f:
                assert f.read() == b"inside"
            # file data after an xattr-carrying inode still reads correctly
            with open(os.path.join(mp, "tagged"), "rb") as f:
                assert f.read() == b"data"

    def test_tarfs_opaque_dirs_export(self, tmp_path):
        """tarfs bootstraps mark opaque dirs; the EROFS export must carry
        the overlay xattr so overlayfs honors opacity."""
        import io
        import tarfile

        from nydus_snapshotter_tpu.models.erofs_image import erofs_from_rafs
        from nydus_snapshotter_tpu.tarfs.bootstrap import tarfs_bootstrap_from_tar

        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w", format=tarfile.GNU_FORMAT) as tf:
            ti = tarfile.TarInfo("d")
            ti.type = tarfile.DIRTYPE
            tf.addfile(ti)
            ti = tarfile.TarInfo("d/.wh..wh..opq")  # opaque marker
            ti.size = 0
            tf.addfile(ti, io.BytesIO(b""))
            ti = tarfile.TarInfo("d/keep")
            ti.size = 4
            tf.addfile(ti, io.BytesIO(b"keep"))
        tar_bytes = buf.getvalue()
        bs = tarfs_bootstrap_from_tar(io.BytesIO(tar_bytes), blob_id="t")
        img = erofs_from_rafs(bs)
        image_path = str(tmp_path / "m.erofs")
        blob_path = str(tmp_path / "t.tar")
        with open(image_path, "wb") as f:
            f.write(img)
        with open(blob_path, "wb") as f:
            f.write(tar_bytes)
            f.write(b"\0" * (-len(tar_bytes) % 512))
        mp = str(tmp_path / "mnt")
        os.mkdir(mp)
        with _MountedWithDevice(image_path, blob_path, mp):
            assert os.getxattr(os.path.join(mp, "d"), "trusted.overlay.opaque") == b"y"
            with open(os.path.join(mp, "d/keep"), "rb") as f:
                assert f.read() == b"keep"


@requires_erofs
class TestOverlayOverErofs:
    def test_two_erofs_layers_under_overlayfs(self, tmp_path):
        """The snapshotter's runtime shape: overlayfs whose lowerdirs are
        kernel-mounted EROFS layers (reference mountRemote overlay
        synthesis, snapshot.go:901-952) — upper-wins, whiteouts delete,
        opaque dirs hide lower contents."""
        lower1 = [
            entry("/app", statmod.S_IFDIR | 0o755),
            entry("/app/keep.txt", statmod.S_IFREG | 0o644, b"from-lower"),
            entry("/app/replaced.txt", statmod.S_IFREG | 0o644, b"old"),
            entry("/app/deleted.txt", statmod.S_IFREG | 0o644, b"bye"),
            entry("/shadowed", statmod.S_IFDIR | 0o755),
            entry("/shadowed/old.txt", statmod.S_IFREG | 0o644, b"hidden"),
        ]
        lower2 = [
            entry("/app", statmod.S_IFDIR | 0o755),
            entry("/app/replaced.txt", statmod.S_IFREG | 0o644, b"new"),
            # whiteout: char dev 0:0 (overlayfs deletion marker)
            entry("/app/deleted.txt", statmod.S_IFCHR, rdev=0),
            # opaque dir: hides /shadowed contents from lower1
            entry("/shadowed", statmod.S_IFDIR | 0o755,
                  xattrs={"trusted.overlay.opaque": b"y"}),
            entry("/shadowed/fresh.txt", statmod.S_IFREG | 0o644, b"visible"),
        ]
        mounts = []
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        try:
            lowers = []
            for i, entries in enumerate((lower1, lower2)):
                img_path = str(tmp_path / f"l{i}.erofs")
                with open(img_path, "wb") as f:
                    f.write(build_erofs(entries))
                mp = str(tmp_path / f"l{i}")
                os.mkdir(mp)
                m = _Mounted(img_path, mp)
                m.__enter__()
                mounts.append(m)
                lowers.append(mp)
            merged = str(tmp_path / "merged")
            os.mkdir(merged)
            # upper layer last in the overlay chain = first in lowerdir
            opts = f"lowerdir={lowers[1]}:{lowers[0]}"
            rc = libc.mount(b"overlay", merged.encode(), b"overlay", 1, opts.encode())
            assert rc == 0, os.strerror(ctypes.get_errno())
            try:
                with open(os.path.join(merged, "app/keep.txt"), "rb") as f:
                    assert f.read() == b"from-lower"
                with open(os.path.join(merged, "app/replaced.txt"), "rb") as f:
                    assert f.read() == b"new"
                assert not os.path.exists(os.path.join(merged, "app/deleted.txt"))
                assert sorted(os.listdir(os.path.join(merged, "shadowed"))) == [
                    "fresh.txt"
                ], "opaque dir must hide lower contents"
            finally:
                libc.umount2(merged.encode(), 2)
        finally:
            for m in mounts:
                m.__exit__(None, None, None)


@requires_erofs
class TestSelfContainedDisk:
    def test_whole_image_disk_mounts_alone(self, tmp_path):
        """write_erofs_disk: one image = metadata + appended tars, chunks
        addressing the primary device — mountable with a single loop
        device (the Kata direct-block shape, tarfs.go:466-571)."""
        import io
        import tarfile

        from nydus_snapshotter_tpu.models.erofs_image import write_erofs_disk
        from nydus_snapshotter_tpu.tarfs.bootstrap import tarfs_bootstrap_from_tar

        payload = RNG.integers(0, 256, 3_000_000, dtype=np.uint8).tobytes()
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w", format=tarfile.GNU_FORMAT) as tf:
            ti = tarfile.TarInfo("data")
            ti.type = tarfile.DIRTYPE
            tf.addfile(ti)
            ti = tarfile.TarInfo("data/blob.bin")
            ti.size = len(payload)
            tf.addfile(ti, io.BytesIO(payload))
            ti = tarfile.TarInfo("data/note")
            ti.size = 5
            tf.addfile(ti, io.BytesIO(b"hello"))
        tar_bytes = buf.getvalue()
        tar_path = str(tmp_path / "layer.tar")
        with open(tar_path, "wb") as f:
            f.write(tar_bytes)

        bs = tarfs_bootstrap_from_tar(io.BytesIO(tar_bytes), blob_id="b0")
        disk_path = str(tmp_path / "whole.erofs")
        with open(disk_path, "w+b") as out:
            data_size = write_erofs_disk(bs, lambda _bid: tar_path, out)
        assert os.path.getsize(disk_path) == data_size

        mp = str(tmp_path / "mnt")
        os.mkdir(mp)
        with _Mounted(disk_path, mp):  # single device, no -o device=
            with open(os.path.join(mp, "data/blob.bin"), "rb") as f:
                assert f.read() == payload
            with open(os.path.join(mp, "data/note"), "rb") as f:
                assert f.read() == b"hello"
