"""Snapshotter core tests: metastore semantics, mount synthesis, label
routing — mirroring what the reference exercises through its unit tests and
integration scenarios (snapshot/snapshot.go, snapshot/process.go)."""

import os

import pytest

from nydus_snapshotter_tpu import constants as C
from nydus_snapshotter_tpu.snapshot import metastore as ms
from nydus_snapshotter_tpu.snapshot.metastore import MetaStore, Usage
from nydus_snapshotter_tpu.snapshot.mount import (
    DmVerityInfo,
    ExtraOption,
    KataVirtualVolume,
    parse_tarfs_dm_verity,
)
from nydus_snapshotter_tpu.snapshot.snapshotter import Snapshotter
from nydus_snapshotter_tpu.utils import errdefs


# ---------------------------------------------------------------------------
# MetaStore
# ---------------------------------------------------------------------------


@pytest.fixture
def store(tmp_path):
    s = MetaStore(str(tmp_path / "metadata.db"))
    yield s
    s.close()


class TestMetaStore:
    def test_create_get_commit_chain(self, store):
        s1 = store.create_snapshot(ms.KIND_ACTIVE, "prep-1")
        assert s1.kind == ms.KIND_ACTIVE and s1.parent_ids == []
        store.commit_active("prep-1", "layer-1", Usage(size=100, inodes=3))

        s2 = store.create_snapshot(ms.KIND_ACTIVE, "prep-2", parent="layer-1")
        assert s2.parent_ids == [s1.id]
        store.commit_active("prep-2", "layer-2", Usage())

        s3 = store.create_snapshot(ms.KIND_ACTIVE, "prep-3", parent="layer-2")
        # immediate parent first, then up the chain
        assert s3.parent_ids == [s2.id, s1.id]

        _, info, usage = store.get_info("layer-1")
        assert info.kind == ms.KIND_COMMITTED and usage.size == 100 and usage.inodes == 3

    def test_create_duplicate_and_bad_parent(self, store):
        store.create_snapshot(ms.KIND_ACTIVE, "a")
        with pytest.raises(errdefs.AlreadyExists):
            store.create_snapshot(ms.KIND_ACTIVE, "a")
        with pytest.raises(errdefs.InvalidArgument):
            # active parent is not committed
            store.create_snapshot(ms.KIND_ACTIVE, "b", parent="a")
        with pytest.raises(errdefs.NotFound):
            store.create_snapshot(ms.KIND_ACTIVE, "c", parent="ghost")

    def test_remove_with_children_refused(self, store):
        store.create_snapshot(ms.KIND_ACTIVE, "p")
        store.commit_active("p", "base", Usage())
        store.create_snapshot(ms.KIND_ACTIVE, "child", parent="base")
        with pytest.raises(errdefs.FailedPrecondition):
            store.remove("base")
        store.remove("child")
        sid, kind = store.remove("base")
        assert kind == ms.KIND_COMMITTED

    def test_update_labels_fieldpaths(self, store):
        store.create_snapshot(ms.KIND_ACTIVE, "k", labels={"a": "1", "b": "2"})
        _, info, _ = store.get_info("k")
        info.labels = {"a": "9", "c": "3"}
        out = store.update_info(info, "labels.a", "labels.c")
        assert out.labels == {"a": "9", "b": "2", "c": "3"}
        info.labels = {"only": "this"}
        out = store.update_info(info)
        assert out.labels == {"only": "this"}

    def test_walk_and_id_map(self, store):
        store.create_snapshot(ms.KIND_ACTIVE, "one")
        store.create_snapshot(ms.KIND_VIEW, "two")
        seen = {}
        store.walk(lambda sid, info: seen.update({info.name: info.kind}))
        assert seen == {"one": ms.KIND_ACTIVE, "two": ms.KIND_VIEW}
        assert set(store.id_map().values()) == {"one", "two"}

    def test_iterate_parent_snapshots(self, store):
        store.create_snapshot(ms.KIND_ACTIVE, "p", labels={C.NYDUS_META_LAYER: "true"})
        store.commit_active("p", "meta", Usage())
        store.create_snapshot(ms.KIND_ACTIVE, "top", parent="meta")
        sid, info = store.iterate_parent_snapshots(
            "top", lambda _sid, i: C.NYDUS_META_LAYER in i.labels
        )
        assert info.name == "meta"
        with pytest.raises(errdefs.NotFound):
            store.iterate_parent_snapshots("top", lambda _sid, i: False)


# ---------------------------------------------------------------------------
# Mount options
# ---------------------------------------------------------------------------


class TestMountOptions:
    def test_extraoption_roundtrip(self):
        eo = ExtraOption(
            source="/s/fs/image/image.boot", config="{}", snapshotdir="/s", fs_version="6"
        )
        opt = eo.encode()
        assert opt.startswith("extraoption=")
        back = ExtraOption.decode(opt)
        assert back == eo

    def test_dm_verity_parse_and_validate(self):
        h = "a" * 64
        di = parse_tarfs_dm_verity(f"4096,2097152,sha256:{h}")
        assert di.blocknum == 4096 and di.offset == 2097152 and di.hash == h
        with pytest.raises(errdefs.InvalidArgument):
            parse_tarfs_dm_verity("garbage")
        with pytest.raises(errdefs.InvalidArgument):
            # offset below data area end
            parse_tarfs_dm_verity(f"4096,512,sha256:{h}")
        bad = DmVerityInfo(hashtype="md5", hash="00", blocknum=1, offset=4096)
        with pytest.raises(errdefs.InvalidArgument):
            bad.validate()

    def test_kata_volume_roundtrip_and_validation(self):
        v = KataVirtualVolume(volume_type="image_guest_pull")
        assert not v.validate()  # image_pull required
        from nydus_snapshotter_tpu.snapshot.mount import ImagePullVolume

        v.image_pull = ImagePullVolume(metadata={"ref": "img"})
        opt = v.encode_option()
        back = KataVirtualVolume.decode_option(opt)
        assert back.volume_type == "image_guest_pull"
        assert back.image_pull.metadata == {"ref": "img"}

        blk = KataVirtualVolume(volume_type="layer_raw_block", source="/dev/loop1")
        assert blk.validate()
        assert KataVirtualVolume(volume_type="bogus", source="x").validate() is False


# ---------------------------------------------------------------------------
# Snapshotter routing / lifecycle (fake fs)
# ---------------------------------------------------------------------------


class FakeFs:
    """Duck-typed L3 facade recording calls (reference tests do the same
    through integration scenarios)."""

    def __init__(self):
        self.mounted = {}
        self.ready = set()
        self.calls = []
        self.stargz = False
        self.tarfs = False
        self.referrer = False

    def mount(self, sid, labels, snapshot):
        self.calls.append(("mount", sid))
        self.mounted[sid] = labels
        self.ready.add(sid)

    def umount(self, sid):
        self.calls.append(("umount", sid))
        self.mounted.pop(sid, None)

    def wait_until_ready(self, sid):
        if sid not in self.ready:
            raise errdefs.NotFound(sid)

    def mount_point(self, sid):
        if sid in self.mounted:
            return f"/mnt/nydus/{sid}"
        raise errdefs.NotFound(sid)

    def bootstrap_file(self, sid):
        return f"/snap/{sid}/fs/image/image.boot"

    def remove_cache(self, digest):
        self.calls.append(("remove_cache", digest))

    def cache_usage(self, digest):
        return Usage(size=42, inodes=1)

    def teardown(self):
        self.calls.append(("teardown",))

    def try_stop_shared_daemon(self):
        self.calls.append(("stop_shared",))

    def check_referrer(self, labels):
        return False

    def referrer_detect_enabled(self):
        return self.referrer

    def try_fetch_metadata(self, labels, meta_path):
        pass

    def stargz_enabled(self):
        return self.stargz

    def is_stargz_data_layer(self, labels):
        return False, None

    def prepare_stargz_meta_layer(self, blob, storage_path, labels):
        pass

    def merge_stargz_meta_layer(self, snapshot):
        pass

    def soci_enabled(self):
        return False

    def is_soci_data_layer(self, labels):
        return False, None

    def prepare_soci_meta_layer(self, blob, storage_path, labels):
        pass

    def merge_soci_meta_layer(self, snapshot):
        pass

    def tarfs_enabled(self):
        return self.tarfs

    def prepare_tarfs_layer(self, labels, sid, upper):
        self.calls.append(("prepare_tarfs", sid))

    def merge_tarfs_layers(self, snapshot, path_fn):
        self.calls.append(("merge_tarfs", snapshot.id))

    def export_block_data(self, snapshot, per_layer, labels, path_fn):
        return []

    def detach_tarfs_layer(self, sid):
        self.calls.append(("detach_tarfs", sid))

    def tarfs_export_enabled(self):
        return False

    def get_instance_extra_option(self, sid):
        return ExtraOption(
            source=self.bootstrap_file(sid),
            config="{}",
            snapshotdir=f"/snap/{sid}",
            fs_version="6",
        )


@pytest.fixture
def sn(tmp_path):
    fs = FakeFs()
    s = Snapshotter(root=str(tmp_path), fs=fs)
    yield s, fs
    s.close()


class TestSnapshotter:
    def test_prepare_native_first_layer_bind_mount(self, sn):
        s, fs = sn
        mounts = s.prepare("prep-1", "")
        assert len(mounts) == 1 and mounts[0].type == "bind"
        assert "rw" in mounts[0].options
        sid = s.ms.get_snapshot("prep-1").id
        assert os.path.isdir(s.upper_path(sid))
        assert os.path.isdir(s.work_path(sid))

    def test_prepare_nydus_data_layer_skips_download(self, sn):
        s, fs = sn
        labels = {
            C.TARGET_SNAPSHOT_REF: "sha256:target",
            C.NYDUS_DATA_LAYER: "true",
        }
        with pytest.raises(errdefs.AlreadyExists):
            s.prepare("prep-data", "", labels)
        # snapshot was committed under the target name with labels intact
        _, info, _ = s.ms.get_info("sha256:target")
        assert info.kind == ms.KIND_COMMITTED
        assert C.NYDUS_DATA_LAYER in info.labels

    def test_prepare_meta_layer_downloads(self, sn):
        s, fs = sn
        labels = {
            C.TARGET_SNAPSHOT_REF: "sha256:meta",
            C.NYDUS_META_LAYER: "true",
        }
        mounts = s.prepare("prep-meta", "", labels)
        # default handler: native bind mount so containerd unpacks bootstrap
        assert mounts[0].type == "bind"

    def test_writable_layer_over_meta_mounts_remote(self, sn):
        s, fs = sn
        # commit a meta layer
        meta_labels = {C.NYDUS_META_LAYER: "true"}
        s.prepare("p-meta", "", {C.TARGET_SNAPSHOT_REF: "ref-x", **meta_labels})
        s.commit("sha256:meta-committed", "p-meta", meta_labels)
        # prepare the container writable layer above it
        mounts = s.prepare("container-rw", "sha256:meta-committed")
        meta_sid, _, _ = s.ms.get_info("sha256:meta-committed")
        assert ("mount", meta_sid) in fs.calls
        assert mounts[0].type == "overlay"
        opts = " ".join(mounts[0].options)
        assert f"/mnt/nydus/{meta_sid}" in opts  # rafs mountpoint as lowerdir
        assert "workdir=" in opts and "upperdir=" in opts

    def test_mounts_active_over_meta(self, sn):
        s, fs = sn
        meta_labels = {C.NYDUS_META_LAYER: "true"}
        s.prepare("p-meta", "", {C.TARGET_SNAPSHOT_REF: "ref-y", **meta_labels})
        s.commit("meta-c", "p-meta", meta_labels)
        s.prepare("rw", "meta-c")
        mounts = s.mounts("rw")
        assert mounts[0].type == "overlay"

    def test_view_of_meta_layer_mounts_on_demand(self, sn):
        s, fs = sn
        meta_labels = {C.NYDUS_META_LAYER: "true"}
        s.prepare("p-m", "", {C.TARGET_SNAPSHOT_REF: "ref-z", **meta_labels})
        s.commit("meta-v", "p-m", meta_labels)
        meta_sid, _, _ = s.ms.get_info("meta-v")
        mounts = s.view("view-1", "meta-v")
        # daemon was not running → View triggers fs.mount itself
        assert ("mount", meta_sid) in fs.calls
        assert mounts[0].type == "overlay"

    def test_view_of_data_layer_rejected(self, sn):
        s, fs = sn
        with pytest.raises(errdefs.AlreadyExists):
            s.prepare("p-d", "", {C.TARGET_SNAPSHOT_REF: "d-ref", C.NYDUS_DATA_LAYER: "y"})
        with pytest.raises(errdefs.InvalidArgument):
            s.view("view-d", "d-ref")

    def test_remove_and_cleanup_orphans(self, sn, tmp_path):
        s, fs = sn
        s.prepare("gone", "")
        sid = s.ms.get_snapshot("gone").id
        s.remove("gone")
        # directory is orphaned until Cleanup
        assert os.path.isdir(s.snapshot_dir(sid))
        s.cleanup()
        assert not os.path.isdir(s.snapshot_dir(sid))

    def test_sync_remove(self, tmp_path):
        fs = FakeFs()
        s = Snapshotter(root=str(tmp_path), fs=fs, sync_remove=True)
        s.prepare("x", "")
        sid = s.ms.get_snapshot("x").id
        s.remove("x")
        assert not os.path.isdir(s.snapshot_dir(sid))
        s.close()

    def test_usage_active_counts_upper(self, sn):
        s, fs = sn
        s.prepare("u", "")
        sid = s.ms.get_snapshot("u").id
        with open(os.path.join(s.upper_path(sid), "f.bin"), "wb") as f:
            f.write(b"x" * 1234)
        u = s.usage("u")
        assert u.size == 1234 and u.inodes == 1

    def test_usage_committed_nydus_adds_cache(self, sn):
        s, fs = sn
        labels = {C.NYDUS_DATA_LAYER: "true", C.CRI_LAYER_DIGEST: "sha256:blob"}
        s.prepare("c", "")
        s.commit("c-committed", "c", labels)
        u = s.usage("c-committed")
        assert u.size >= 42  # cache usage added

    def test_proxy_driver_mounts(self, tmp_path):
        fs = FakeFs()
        s = Snapshotter(root=str(tmp_path), fs=fs, fs_driver=C.FS_DRIVER_PROXY)
        labels = {C.TARGET_SNAPSHOT_REF: "t-proxy", C.CRI_LAYER_DIGEST: "sha256:d"}
        with pytest.raises(errdefs.AlreadyExists):
            s.prepare("pp", "", labels)
        _, info, _ = s.ms.get_info("t-proxy")
        assert info.labels.get(C.NYDUS_PROXY_MODE) == "true"
        s.close()

    def test_stargz_layer_routing(self, tmp_path):
        fs = FakeFs()
        fs.stargz = True

        class Blob:
            pass

        fs.is_stargz_data_layer = lambda labels: (True, Blob())
        s = Snapshotter(root=str(tmp_path), fs=fs)
        labels = {C.TARGET_SNAPSHOT_REF: "t-sgz"}
        with pytest.raises(errdefs.AlreadyExists):
            s.prepare("sgz", "", labels)
        _, info, _ = s.ms.get_info("t-sgz")
        assert info.labels.get(C.STARGZ_LAYER) == "true"
        s.close()

    def test_tarfs_layer_routing(self, tmp_path):
        fs = FakeFs()
        fs.tarfs = True
        s = Snapshotter(root=str(tmp_path), fs=fs)
        labels = {C.TARGET_SNAPSHOT_REF: "t-tarfs"}
        with pytest.raises(errdefs.AlreadyExists):
            s.prepare("tfs", "", labels)
        assert any(c[0] == "prepare_tarfs" for c in fs.calls)
        s.close()

    def test_extra_options_mount(self, tmp_path):
        fs = FakeFs()
        s = Snapshotter(root=str(tmp_path), fs=fs, enable_nydus_overlayfs=True)
        meta_labels = {C.NYDUS_META_LAYER: "true"}
        s.prepare("m", "", {C.TARGET_SNAPSHOT_REF: "m-ref", **meta_labels})
        s.commit("m-c", "m", meta_labels)
        mounts = s.prepare("rw2", "m-c")
        assert mounts[0].type == "fuse.nydus-overlayfs"
        assert any(o.startswith("extraoption=") for o in mounts[0].options)
        s.close()

    def test_kata_layer_raw_block_volumes_emitted_top_first(self, tmp_path):
        """Per-layer kata raw-block volumes must appear in parent-walk
        (top-down) order, matching the reference's mountWithTarfsVolume
        loop that appends while walking from the topmost committed layer
        to the bottom (mount_option.go:211-242)."""
        fs = FakeFs()
        fs.tarfs = True
        fs.get_instance_annotations = lambda sid: {
            C.NYDUS_TARFS_LAYER: "blob-top",
            C.NYDUS_LAYER_BLOCK_INFO: "4096,2097152,sha256:" + "a" * 64,
        }
        fs.tarfs_layer_disk_path = lambda blob_id: f"/disk/{blob_id}.layer.disk"
        s = Snapshotter(root=str(tmp_path), fs=fs, enable_kata_volume=True)

        # three committed tarfs layers: bottom -> mid -> top (the ro-layer
        # prepare commits under the target ref and raises AlreadyExists)
        parent = ""
        for name in ("bottom", "mid", "top"):
            labels = {C.NYDUS_TARFS_LAYER: f"blob-{name}"}
            with pytest.raises(errdefs.AlreadyExists):
                s.prepare(
                    f"p-{name}", parent, {C.TARGET_SNAPSHOT_REF: f"ref-{name}", **labels}
                )
            parent = f"ref-{name}"

        mounts = s.prepare("rw-kata", "ref-top")
        opts = [o for o in mounts[0].options if o.startswith("io.katacontainers.volume=")]
        assert len(opts) == 3
        sources = [KataVirtualVolume.decode_option(o).source for o in opts]
        assert sources == [
            "/disk/blob-top.layer.disk",
            "/disk/blob-mid.layer.disk",
            "/disk/blob-bottom.layer.disk",
        ]
        for o in opts:
            assert KataVirtualVolume.decode_option(o).volume_type == "layer_raw_block"
        s.close()
