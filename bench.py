"""Conversion benchmark: full-path OCI→RAFS convert throughput per chip.

The headline ``value`` is what BASELINE.md actually targets — end-to-end
RAFS conversion (tar parse → CDC chunking → SHA-256 chunk digests → dedup →
lz4 compress → blob assembly + blob digest, `converter.convert.pack_layer`)
— over a node:21-shaped synthetic image: log-normal file sizes (thousands
of small files, a few big ones), a 40/40/20 text/binary/random
compressibility mix, and log-spread layer sizes (BASELINE configs #1-#3
without network access). The bare engine rate (chunk+digest only, the
number earlier rounds reported as the headline) is still measured and
reported under ``detail.engine_gibps``.

Engine selection is measured, not assumed (SURVEY §7 hard-part #3):

- **Boundaries**: the Pallas gear-bitmap kernel (ops/gear_pallas.py) when a
  TPU answers, else the native C++ fused arm / numpy windowed fallback.
- **Digests**: host (SHA-NI x3 batch scheduler) vs device (bucketed
  uint32-lane SHA-256) raced end-to-end on a calibration slice.
- **Dict probe**: native C++ open-addressing probe on a single chip (XLA
  TPU gathers are element-serial, measured ~1 µs/element), the sharded
  all_to_all path on multi-chip meshes.

Prints ONE JSON line: metric, value (GiB/s on this chip), unit, vs_baseline
(fraction of the 2.5 GiB/s per-chip share of the 20 GiB/s v5e-8 target),
plus engine/probe arms, device probe outcome, and a full-path dict-dedup
run (image B converted against image A's chunk dict, measured dedup ratio).
"""

from __future__ import annotations

import io
import json
import os
import sys
import tarfile
import time

import numpy as np

PER_CHIP_TARGET_GIBPS = 20.0 / 8.0  # north-star 20 GiB/s on a v5e-8

CORPUS_MIB = int(os.environ.get("NTPU_BENCH_MIB", "384"))
IMAGE_MIB = int(os.environ.get("NTPU_BENCH_IMAGE_MIB", "192"))
CHUNK_SIZE = 0x10000  # 64 KiB average: matches dedup-grade chunking
N_FILES = 24
CALIBRATE_MIB = 16
REPS = 3


# ---------------------------------------------------------------------------
# Corpora
# ---------------------------------------------------------------------------


def build_corpus(total_mib: int, n_files: int) -> list[bytes]:
    """Flat corpus (uniform random blocks + exact duplicates) — feeds the
    bare-engine measurement and the engine race."""
    rng = np.random.default_rng(42)
    per = total_mib * (1 << 20) // n_files
    base = rng.integers(0, 256, per, dtype=np.uint8).tobytes()
    files = []
    for i in range(n_files):
        if i % 3 == 2:
            files.append(base)  # duplicated content: dedup work is real
        else:
            files.append(rng.integers(0, 256, per, dtype=np.uint8).tobytes())
    return files


_TEXT_BASE: np.ndarray | None = None


def _text_base(rng) -> np.ndarray:
    """1 MiB of word-like ASCII (compresses ~3-4x under lz4, like source
    trees / node_modules JS)."""
    global _TEXT_BASE
    if _TEXT_BASE is None:
        words = [
            rng.integers(97, 123, int(rng.integers(3, 11)), dtype=np.uint8)
            for _ in range(400)
        ]
        parts = []
        n = 0
        while n < (1 << 20):
            w = words[int(rng.integers(0, len(words)))]
            parts.append(w)
            parts.append(np.frombuffer(b" ", dtype=np.uint8))
            n += len(w) + 1
        _TEXT_BASE = np.concatenate(parts)[: 1 << 20]
    return _TEXT_BASE


def build_file_pool(total_mib: int, seed: int) -> list[bytes]:
    """Shared file pool: cross-image dedup in registries comes from the
    SAME files appearing in many images (base layers, npm packages), so
    the pool is whole files reused verbatim — offset-shifted byte ranges
    would defeat whole-file-sized CDC chunks and understate dedup."""
    rng = np.random.default_rng(seed)
    total = total_mib << 20
    files = []
    used = 0
    while used < total:
        size = int(np.clip(rng.lognormal(8.5, 2.0), 128, 8 << 20))
        r = rng.random()
        kind = "text" if r < 0.4 else ("binary" if r < 0.8 else "random")
        files.append(_gen_file(rng, size, kind))
        used += size
    return files


def _gen_file(rng, size: int, kind: str) -> bytes:
    if kind == "text":
        base = _text_base(rng)
        reps = -(-size // base.size)
        off = int(rng.integers(0, base.size))
        return np.concatenate([base[off:]] + [base] * reps)[:size].tobytes()
    if kind == "binary":
        # ELF-ish: random bytes with zero runs (compresses ~2x)
        data = rng.integers(0, 256, size, dtype=np.uint8)
        mask = rng.random(size) < 0.55
        data[mask] = 0
        return data.tobytes()
    return rng.integers(0, 256, size, dtype=np.uint8).tobytes()


def build_node_shaped_layers(
    total_mib: int,
    seed: int,
    pool: list[bytes] | None = None,
    reuse_fraction: float = 0.0,
) -> tuple[list[bytes], dict]:
    """Synthetic image with a realistic shape: log-normal file sizes
    (median ~5 KiB, tail into MiBs — many small files like node:21's
    node_modules), 40/40/20 text/binary/random compressibility mix,
    6 log-spread layers (one big rootfs layer, small app layers).

    ``pool``/``reuse_fraction``: that fraction of files takes its bytes
    from the shared content pool instead of fresh generation — the
    cross-image overlap that makes chunk-dict dedup hits real.
    """
    rng = np.random.default_rng(seed)
    total = total_mib << 20
    weights = np.asarray([32.0, 16.0, 8.0, 4.0, 2.0, 2.0])
    layer_bytes = (weights / weights.sum() * total).astype(np.int64)
    layers = []
    n_files = 0
    kind_bytes = {"text": 0, "binary": 0, "random": 0, "pooled": 0}
    for li, budget in enumerate(layer_bytes):
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w", format=tarfile.GNU_FORMAT) as tf:
            used = 0
            fi = 0
            while used < budget:
                use_pool = pool is not None and rng.random() < reuse_fraction
                if use_pool:
                    data = pool[int(rng.integers(0, len(pool)))]
                    kind_bytes["pooled"] += len(data)
                else:
                    size = int(np.clip(rng.lognormal(8.5, 2.0), 128, 8 << 20))
                    size = min(size, int(budget - used)) or 128
                    r = rng.random()
                    kind = (
                        "text" if r < 0.4 else ("binary" if r < 0.8 else "random")
                    )
                    data = _gen_file(rng, size, kind)
                    kind_bytes[kind] += size
                ti = tarfile.TarInfo(f"layer{li}/d{fi % 97}/f{fi}.bin")
                ti.size = len(data)
                tf.addfile(ti, io.BytesIO(data))
                used += len(data)
                fi += 1
                n_files += 1
        layers.append(buf.getvalue())
    info = {
        "files": n_files,
        "layers": len(layers),
        "mix_bytes_mib": {k: round(v / (1 << 20), 1) for k, v in kind_bytes.items()},
    }
    return layers, info


# ---------------------------------------------------------------------------
# Engine race (bare engine, calibration slice; device arms in subprocesses)
# ---------------------------------------------------------------------------

_ENGINE_CHILD = """
import os, sys, time
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/ntpu_jax_cache")
sys.path.insert(0, {repo!r})
import numpy as np
from nydus_snapshotter_tpu.ops.chunker import ChunkDigestEngine
rng = np.random.default_rng(7)
sample = [rng.integers(0, 256, {mib} << 19, dtype=np.uint8).tobytes() for _ in range(2)]
eng = ChunkDigestEngine(chunk_size={chunk_size}, mode="cdc", **{kwargs!r})
eng.process_many(sample)  # compile warm-up
t = time.time()
eng.process_many(sample)
print(time.time() - t)
"""

# Candidate engine arms raced end-to-end (process_many on the calibration
# slice). "host" runs in-process; device arms run in a SUBPROCESS with a
# hard timeout so a hostile backend (slow compile, wedged device tunnel)
# loses the race instead of hanging the bench — the persistent JAX compile
# cache carries the child's compilation over to the real run.
ENGINE_ARMS = {
    "host": {"backend": "hybrid"},
    "device_digest": {"backend": "hybrid", "digest_backend": "jax"},
    "device_all": {"backend": "jax", "digest_backend": "jax"},
    # full-path two-dispatch composition (ops/fused_convert): the whole
    # batch as one gear+compaction dispatch and one gather+digest dispatch
    "device_fused": {"backend": "fused"},
}


def _run_child_watchdog(argv: list[str], timeout: float):
    """Run a child under a HARD watchdog: the wait happens on a worker
    thread, so a child wedged in uninterruptible device I/O (the
    BENCH_r05 "device probe hung >120s" failure: subprocess timeout fired
    but the kill/reap itself stalled on the wedged TPU tunnel) can never
    stall the bench main thread. On timeout the child's whole process
    group is SIGKILLed and the reaper thread is abandoned (daemon) if
    even the reap hangs.

    Returns ``(returncode, stdout, stderr)`` or ``None`` on timeout/spawn
    failure.
    """
    import signal
    import subprocess
    import threading

    try:
        proc = subprocess.Popen(
            argv,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            start_new_session=True,  # own pgid: killpg reaps grandchildren
        )
    except OSError:
        return None
    result = {}

    def _wait():
        try:
            result["out"], result["err"] = proc.communicate()
        except Exception as e:  # noqa: BLE001 — watchdog must not raise
            result["exc"] = e

    waiter = threading.Thread(target=_wait, daemon=True)
    waiter.start()
    waiter.join(timeout)
    if waiter.is_alive() or "exc" in result:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            pass
        waiter.join(5.0)  # give the reap a moment; abandon it if stuck
        return None
    return proc.returncode, result.get("out", ""), result.get("err", "")


def _time_engine_child(repo: str, chunk_size: int, kwargs: dict):
    """Timed process_many in a subprocess; None on failure/timeout."""
    child = _ENGINE_CHILD.format(
        repo=repo, mib=CALIBRATE_MIB, chunk_size=chunk_size, kwargs=kwargs
    )
    res = _run_child_watchdog([sys.executable, "-c", child], timeout=240)
    if res is None or res[0] != 0:
        return None
    try:
        return float(res[1].strip().splitlines()[-1])
    except (ValueError, IndexError):
        return None


def calibrate_engine(chunk_size: int, repo: str, device_ok: bool):
    """(winning arm name, device_executes, timings, probe_order) from the
    end-to-end race. ``device_executes`` is False when every device arm
    failed outright (not merely lost) — the device must then not be used
    for anything, including the dict probe.

    Probe ordering (VERDICT r5 top_next): the FUSED FULL-PATH arm is the
    FIRST child dispatched into a device tunnel window — five rounds of
    ``device:false`` were spent on kernel micro-stages before the one
    number the north star needs, and windows last ~100 s. The dispatched
    order is returned so the bench JSON records it and a regression back
    to micro-stages-first is visible in the artifact diff."""
    from nydus_snapshotter_tpu.ops.chunker import ChunkDigestEngine

    # fullpath first, micro arms after — the host arm runs in-process
    # and never burns tunnel time, so it is not part of the window order
    device_order = ("device_fused", "device_digest", "device_all")
    probe_order: list[str] = []
    times = {}
    if device_ok:
        for arm in device_order:
            probe_order.append(arm)
            dt = _time_engine_child(repo, chunk_size, ENGINE_ARMS[arm])
            if dt is not None:
                times[arm] = dt

    rng = np.random.default_rng(7)
    sample = [rng.integers(0, 256, CALIBRATE_MIB << 19, dtype=np.uint8).tobytes()
              for _ in range(2)]
    host = ChunkDigestEngine(chunk_size=chunk_size, mode="cdc", **ENGINE_ARMS["host"])
    host.process_many(sample)  # thread-pool / build warm-up
    t = time.time()
    host.process_many(sample)
    times["host"] = time.time() - t

    winner = min(times, key=times.get)
    device_executes = any(k != "host" for k in times)
    return (
        winner,
        device_executes,
        {k: round(v, 3) for k, v in times.items()},
        probe_order,
    )


def build_probe(dict_digest_bytes: bytes, device_ok: bool):
    """(probe fn, arm name) for a chunk dict of raw 32-byte digests.

    Probe arm: native host table on one chip (device gathers are
    element-serial), sharded all_to_all on real meshes; pure-python set as
    the last resort. Never touches jax backend init unless the device
    already answered (a wedged tunnel must not hang the bench).
    """
    from nydus_snapshotter_tpu.ops import native_cdc
    from nydus_snapshotter_tpu.parallel import mesh as mesh_lib
    from nydus_snapshotter_tpu.parallel.sharded_dict import ShardedChunkDict

    dict_digests = (
        np.frombuffer(dict_digest_bytes, dtype="<u4").reshape(-1, 8)
        if dict_digest_bytes
        else np.zeros((0, 8), np.uint32)
    )
    if device_ok:
        sdict = ShardedChunkDict(dict_digests, mesh_lib.make_mesh(1))
        sdict.lookup_digests([dict_digest_bytes[:32]] if dict_digest_bytes else [])
        return sdict.lookup_digests, (
            "host-native" if sdict._use_host_probe() else "device"
        )
    if native_cdc.dict_probe_available():
        from nydus_snapshotter_tpu.parallel.sharded_dict import (
            MAX_PROBE,
            _build_host_tables,
        )

        keys, values = _build_host_tables(dict_digests, 1)

        def probe(digests):
            q = np.frombuffer(b"".join(digests), dtype="<u4").reshape(-1, 8)
            return native_cdc.dict_probe_native(
                q, keys.reshape(-1, 8), values.reshape(-1), 1, keys.shape[1], MAX_PROBE
            )

        return probe, "host-native"

    dict_set = {
        dict_digest_bytes[i : i + 32] for i in range(0, len(dict_digest_bytes), 32)
    }
    return (lambda digests: np.asarray([d in dict_set for d in digests])), "host-set"


def engine_flat_run(engine, probe) -> dict:
    """Bare-engine rate on the flat corpus (chunk+digest+probe only) —
    rounds 1-2's headline, kept for comparability."""
    files = build_corpus(CORPUS_MIB, N_FILES)
    total_bytes = sum(len(f) for f in files)
    best = None
    for _ in range(REPS):
        arrs = [np.frombuffer(f, dtype=np.uint8) for f in files]
        t0 = time.time()
        metas = engine.process_many(arrs)
        all_digests = [m.digest for f in metas for m in f]
        hits = np.asarray(probe(all_digests))
        elapsed = time.time() - t0
        n_hits = int(hits.sum() if hits.dtype == bool else (hits >= 0).sum())
        if best is None or elapsed < best[0]:
            best = (elapsed, len(all_digests), n_hits)
    return {
        "engine_gibps": round(total_bytes / best[0] / (1 << 30), 4),
        "corpus_mib": CORPUS_MIB,
        "n_chunks": best[1],
        "dict_hits": best[2],
    }


# ---------------------------------------------------------------------------
# Full-path conversion (the headline)
# ---------------------------------------------------------------------------


def _pack_kwargs(winner: str) -> dict:
    """PackOption fields matching the raced engine arm, so the headline
    full-path run actually uses the winning configuration."""
    if winner == "device_fused":
        return {"backend": "fused"}
    if winner == "device_all":
        return {"backend": "jax"}
    if winner == "device_digest":
        return {"backend": "hybrid", "digest_backend": "jax"}
    return {"backend": "hybrid"}


def _pack_layers(layers: list[bytes], opt, chunk_dict=None, stats=None) -> list:
    """Pack an image's layers in parallel (ordered results) — the
    reference's per-layer parallelism (one nydus-image process per layer);
    here the native engine, liblz4, and hashlib all drop the GIL, so
    threads scale on multi-core hosts and cost nothing on one core."""
    from concurrent.futures import ThreadPoolExecutor

    from nydus_snapshotter_tpu.converter.convert import pack_layer

    # Same auto-degradation as converter/stream._pack_threads: a pool on a
    # 1-core host measurably costs ~13% (GIL handoffs + contention) over
    # the serial walk it cannot beat.
    if len(layers) == 1 or (os.cpu_count() or 1) == 1:
        return [
            pack_layer(t, opt, chunk_dict=chunk_dict, stats=stats) for t in layers
        ]

    def _one(t):
        # Per-layer stats dict, merged after: the shared-dict accumulation
        # inside pack_stream is not thread-safe.
        st: dict = {}
        r = pack_layer(t, opt, chunk_dict=chunk_dict, stats=st)
        return r, st

    with ThreadPoolExecutor(max_workers=min(8, len(layers))) as pool:
        results = list(pool.map(_one, layers))
    if stats is not None:
        for _r, st in results:
            for k, v in st.items():
                stats[k] = stats.get(k, 0.0) + v
    return [r for r, _st in results]


def full_path_run(layers: list[bytes], opt) -> tuple[float, list, list, dict, dict]:
    """Best-of-REPS wall time converting every layer of the image; also
    returns a per-stage wall breakdown (scan / chunk_digest / dedup /
    assemble / bootstrap) measured on a SEPARATE layer-serial pass —
    parallel-layer stage clocks would sum thread wall time (including
    GIL/CPU contention) to more than the elapsed wall and mislead — plus
    a ``pipeline`` dict capturing the stage-parallel executor's overlap
    win (parallel vs serial wall, per-stage busy/utilization, worker
    counts and queue high-water) so the perf trajectory records it."""
    from nydus_snapshotter_tpu.converter.convert import pack_layer
    from nydus_snapshotter_tpu.converter.stream import _pack_threads
    from nydus_snapshotter_tpu.parallel import pipeline as pipeline_mod

    total = sum(len(t) for t in layers)
    best = None
    out = None
    snap_before = pipeline_mod.snapshot_counters()
    for _ in range(REPS):
        t0 = time.time()
        packed = _pack_layers(layers, opt)
        elapsed = time.time() - t0
        if best is None or elapsed < best:
            best = elapsed
            out = packed
    snap_after = pipeline_mod.snapshot_counters()
    stats: dict = {}
    t0 = time.time()
    for t in layers:
        pack_layer(t, opt, stats=stats)
    serial_wall = time.time() - t0
    blobs = [b for b, _ in out]
    results = [r for _, r in out]
    breakdown = {k: round(v, 4) for k, v in sorted(stats.items())}
    breakdown["serial_wall"] = round(serial_wall, 4)
    breakdown["parallel_wall"] = round(best, 4)

    n_threads = _pack_threads()
    pcfg = pipeline_mod.resolve_config(n_threads)
    runs = snap_after["runs"] - snap_before["runs"]
    stage_busy = {
        k: round((snap_after["stage_busy_s"][k] - snap_before["stage_busy_s"][k]) / REPS, 4)
        for k in snap_after["stage_busy_s"]
    }
    pipeline_info = {
        "enabled": pcfg.enabled,
        "engaged_runs": runs / REPS if runs else 0.0,
        "workers": {
            "pack_threads": n_threads,
            "chunk": pcfg.chunk_workers,
            "compress": pcfg.compress_workers,
        },
        "parallel_wall": round(best, 4),
        "serial_wall": round(serial_wall, 4),
        "speedup": round(serial_wall / max(1e-9, best), 4),
        # busy seconds per rep; utilization = busy / (wall × workers)
        "stage_busy_s": stage_busy,
        "stage_utilization": {
            "chunk": round(
                stage_busy.get("chunk", 0.0) / max(1e-9, best * pcfg.chunk_workers), 4
            ),
            "compress": round(
                stage_busy.get("compress", 0.0)
                / max(1e-9, best * pcfg.compress_workers),
                4,
            ),
        },
        "queue_high_water_bytes": snap_after["queue_high_water_bytes"],
        "shed_bytes": snap_after["shed_bytes"] - snap_before["shed_bytes"],
    }
    # Both lanes produce identical blobs; the headline is the best measured
    # full-path wall (the serial pass even carries stats overhead, so this
    # is conservative — it only de-noises, never flatters).
    best = min(best, serial_wall)
    return total / best / (1 << 30), blobs, results, breakdown, pipeline_info


def dedup_shaped_run(opt, pool: list[bytes]) -> dict:
    """Full-path BASELINE configs #2/#3: convert image A (all content from
    the shared pool), build its chunk dict from the merged bootstrap, then
    convert image B (~50% pool reuse) against the dict. Dedup ratio =
    bytes of B's chunks resolved to A's blobs / B's total chunk bytes."""
    from nydus_snapshotter_tpu.converter.convert import (
        Merge,
        bootstrap_from_layer_blob,
    )
    from nydus_snapshotter_tpu.converter.types import MergeOption
    from nydus_snapshotter_tpu.models.bootstrap import Bootstrap, ChunkDict

    layers_a, _ = build_node_shaped_layers(
        min(IMAGE_MIB, 128), seed=101, pool=pool, reuse_fraction=1.0
    )
    layers_b, _ = build_node_shaped_layers(
        min(IMAGE_MIB, 128), seed=202, pool=pool, reuse_fraction=0.5
    )

    t0 = time.time()
    packed_a = _pack_layers(layers_a, opt)
    t_a = time.time() - t0
    merged = Merge([b for b, _ in packed_a], MergeOption(with_tar=False))
    cdict = ChunkDict(Bootstrap.from_bytes(merged.bootstrap))

    t1 = time.time()
    packed_b = _pack_layers(layers_b, opt, chunk_dict=cdict)
    t_b = time.time() - t1

    own_ids = {r.blob_id for _, r in packed_b}
    dedup_bytes = 0
    total_chunk_bytes = 0
    for blob, _res in packed_b:
        bs = bootstrap_from_layer_blob(blob)
        for c in bs.chunks:
            total_chunk_bytes += c.uncompressed_size
            if bs.blobs[c.blob_index].blob_id not in own_ids:
                dedup_bytes += c.uncompressed_size
    bytes_a = sum(len(t) for t in layers_a)
    bytes_b = sum(len(t) for t in layers_b)
    return {
        "image_mib": round(bytes_a / (1 << 20)),
        "layers": len(layers_a),
        "dict_chunks": len(cdict),
        "build_dict_gibps": round(bytes_a / t_a / (1 << 30), 4),
        "convert_vs_dict_gibps": round(bytes_b / t_b / (1 << 30), 4),
        "dedup_ratio": round(dedup_bytes / max(1, total_chunk_bytes), 4),
    }


def _manifest_files(gen_of) -> list:
    """Materialize the committed REAL Ubuntu manifest as tar members.

    The manifest machinery (including the per-(path, generation) content
    synthesis) lives in scenario/corpus.py now, shared with the scenario
    engine's real-tree corpora so every real-layout consumer synthesizes
    the identical bytes.
    """
    from nydus_snapshotter_tpu.scenario import corpus as _corpus

    return _corpus.real_tree_members(gen_of=gen_of)


def _members_to_tar(members) -> bytes:
    from nydus_snapshotter_tpu.scenario import corpus as _corpus

    return _corpus.members_to_tar(members)


def real_image_run(opt) -> dict:
    """BASELINE configs #1/#2 on a REAL image shape (VERDICT r4 next #6).

    Image A = the real Ubuntu rootfs tree (single layer, as the real
    ubuntu base image ships). Its merged bootstrap is re-emitted in the
    REAL nydus v6 on-disk layout (models/nydus_real_write) and loaded
    back through the real-bootstrap parser as the chunk dict — the same
    round trip `--chunk-dict bootstrap=<real image>` takes. Image B = the
    upgraded rootfs (~25% of files changed) converted against that dict;
    the dedup ratio counts B's bytes resolved into A's blobs.
    """
    from nydus_snapshotter_tpu.converter.convert import (
        Merge,
        bootstrap_from_layer_blob,
        pack_layer,
    )
    from nydus_snapshotter_tpu.converter.types import MergeOption
    from nydus_snapshotter_tpu.models.bootstrap import Bootstrap, ChunkDict
    from nydus_snapshotter_tpu.models.nydus_real import load_any_bootstrap
    from nydus_snapshotter_tpu.models.nydus_real_write import (
        real_from_bootstrap,
        write_real_v6,
    )

    # RAFS v6's on-disk chunk index is a fixed grid, so REAL v6 images are
    # fixed-chunked (the nydus default; the fixture uses 1 MiB). Pack both
    # images fixed so the real-layout round trip is valid and B's chunk
    # digests can actually hit A's grid.
    from dataclasses import replace

    ropt = replace(opt, chunking="fixed")
    members_a = _manifest_files(lambda p: 0)
    tar_a = _members_to_tar(members_a)
    t0 = time.time()
    blob_a, res_a = pack_layer(tar_a, ropt)
    t_a = time.time() - t0
    merged = Merge([blob_a], MergeOption(with_tar=False))
    # real-layout round trip: our merged bootstrap -> REAL v6 bytes ->
    # real parser -> chunk dict (what the reference hands nydus-image)
    real_v6 = write_real_v6(
        real_from_bootstrap(Bootstrap.from_bytes(merged.bootstrap))
    )
    cdict = ChunkDict(load_any_bootstrap(real_v6))

    def gen_b(p):  # ~25% of files changed: an apt-upgrade-sized delta
        import hashlib as h

        return 1 if h.sha256(p.encode()).digest()[0] < 64 else 0

    tar_b = _members_to_tar(_manifest_files(gen_b))
    t1 = time.time()
    blob_b, res_b = pack_layer(tar_b, ropt, chunk_dict=cdict)
    t_b = time.time() - t1

    bs_b = bootstrap_from_layer_blob(blob_b)
    own = {res_b.blob_id}
    dedup_bytes = sum(
        c.uncompressed_size
        for c in bs_b.chunks
        if bs_b.blobs[c.blob_index].blob_id not in own
    )
    total_chunk_bytes = sum(c.uncompressed_size for c in bs_b.chunks)

    # VERDICT r5 #8: real-vs-real CROSS-TREE dedup — the second
    # real-derived tree (a sibling image: package subset + changed-file
    # delta, tools/extract_real_manifest.py --derive-tree2) converted
    # against tree1's real-bootstrap dict. The content-synthesis caveat
    # rides in the result: layout/chunk-grid is real, bytes are not.
    from nydus_snapshotter_tpu.scenario.corpus import cross_tree_dedup

    cross_tree = cross_tree_dedup(ropt)
    return {
        "source": "real ubuntu rootfs tree (committed manifest of the "
        "reference's v6 fixture; content synthesized per file)",
        "inodes": len(members_a),
        "image_mib": round(len(tar_a) / (1 << 20), 1),
        "convert_gibps": round(len(tar_a) / t_a / (1 << 30), 4),
        "dict_source": "REAL v6 layout round trip (write_real_v6 -> "
        "load_any_bootstrap)",
        "dict_chunks": len(cdict),
        "convert_vs_real_dict_gibps": round(len(tar_b) / t_b / (1 << 30), 4),
        "dedup_ratio": round(dedup_bytes / max(1, total_chunk_bytes), 4),
        "cross_tree_dedup": cross_tree,
    }


def stargz_zran_run(opt) -> dict:
    """BASELINE config #4 shape: eStargz index build + OCI-zran (targz-ref)
    conversion of a python:3.12-like compressible layer. Reports MiB/s of
    compressed input indexed (the blob itself is never re-stored)."""
    import gzip

    from nydus_snapshotter_tpu.converter.zran import pack_gzip_layer
    from nydus_snapshotter_tpu.stargz import index as stargz_index

    layers, _info = build_node_shaped_layers(min(IMAGE_MIB, 64), seed=404)
    raw = layers[0]
    raw_gz = gzip.compress(raw, compresslevel=6)

    t0 = time.time()
    bs = pack_gzip_layer(raw_gz, opt)
    t_zran = time.time() - t0

    # eStargz TOC -> bootstrap on the same content shape (the index path
    # the stargz resolver feeds; TOC synthesized from the layer listing,
    # using each member's real header offset as its stream offset so the
    # consecutive-offset deltas bootstrap_from_toc derives stay within the
    # blob).
    import hashlib

    entries = []
    with tarfile.open(fileobj=io.BytesIO(raw)) as tf:
        for m in tf.getmembers():
            if m.isreg():
                data = tf.extractfile(m).read()
                entries.append(
                    {
                        "name": m.name,
                        "type": "reg",
                        "size": m.size,
                        "offset": m.offset,
                        "digest": "sha256:" + hashlib.sha256(data).hexdigest(),
                    }
                )
    toc = {"version": 1, "entries": entries}
    t1 = time.time()
    toc_bs = stargz_index.bootstrap_from_toc(toc, blob_id="0" * 64)
    t_toc = time.time() - t1

    return {
        "layer_mib": round(len(raw) / (1 << 20), 1),
        "gzip_mib": round(len(raw_gz) / (1 << 20), 1),
        "zran_index_mibps": round(len(raw_gz) / (1 << 20) / t_zran, 1),
        "zran_chunks": len(bs.chunks),
        "estargz_toc_entries": len(entries),
        "toc_bootstrap_mibps": round(len(raw) / (1 << 20) / t_toc, 1),
    }


_LAZY_READ_CHILD = """
import json, sys
sys.path.insert(0, {repo!r})
from tools.lazy_read_profile import profile
print(json.dumps(profile(mib=8, workers=4, latency_ms=2.0)))
"""


def lazy_read_run(repo: str, timeout: float = 240.0) -> dict:
    """Cold vs warm lazy-read profile (tools/lazy_read_profile.py) in a
    child under the hard watchdog: the fetch scheduler spins worker
    threads, and a wedged pool must cost the bench one timeout, not a
    hang. Returns the profile dict or a {'error': ...} marker."""
    res = _run_child_watchdog(
        [sys.executable, "-c", _LAZY_READ_CHILD.format(repo=repo)], timeout=timeout
    )
    if res is None:
        return {"error": f"lazy-read profile hung >{timeout:.0f}s (watchdog killed it)"}
    rc, stdout, stderr = res
    if rc != 0:
        tail = stderr.strip().splitlines()[-1] if stderr.strip() else ""
        return {"error": f"lazy-read profile exited rc={rc}: {tail}"[:200]}
    try:
        return json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "lazy-read profile produced no JSON"}


_SNAPSHOT_OPS_CHILD = """
import json, sys
sys.path.insert(0, {repo!r})
from tools.snapshot_profile import profile
print(json.dumps(profile(layers=8, pods=8)))
"""


def snapshot_ops_run(repo: str, timeout: float = 240.0) -> dict:
    """Snapshot control-plane storm (tools/snapshot_profile.py) in a child
    under the hard watchdog: serial vs concurrent wall plus p50/p99 per
    op, with the identity gate evaluated in-process. A wedged prepare
    board or usage accountant costs one timeout, not a hang."""
    res = _run_child_watchdog(
        [sys.executable, "-c", _SNAPSHOT_OPS_CHILD.format(repo=repo)], timeout=timeout
    )
    if res is None:
        return {"error": f"snapshot profile hung >{timeout:.0f}s (watchdog killed it)"}
    rc, stdout, stderr = res
    if rc != 0:
        tail = stderr.strip().splitlines()[-1] if stderr.strip() else ""
        return {"error": f"snapshot profile exited rc={rc}: {tail}"[:200]}
    try:
        return json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "snapshot profile produced no JSON"}


_TRACE_CHILD = """
import json, sys
sys.path.insert(0, {repo!r})
from tools.trace_profile import profile
print(json.dumps(profile(layers=4, pods=4, reps=2)))
"""


def trace_run(repo: str, timeout: float = 240.0) -> dict:
    """Trace overhead profile (tools/trace_profile.py) in a child under
    the hard watchdog: enabled-vs-disabled storm overhead, spans/sec into
    the ring, drops, and the end-to-end Prepare tree gate."""
    res = _run_child_watchdog(
        [sys.executable, "-c", _TRACE_CHILD.format(repo=repo)], timeout=timeout
    )
    if res is None:
        return {"error": f"trace profile hung >{timeout:.0f}s (watchdog killed it)"}
    rc, stdout, stderr = res
    if rc != 0:
        tail = stderr.strip().splitlines()[-1] if stderr.strip() else ""
        return {"error": f"trace profile exited rc={rc}: {tail}"[:200]}
    try:
        return json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "trace profile produced no JSON"}


_CHUNK_DICT_CHILD = """
import json, sys
sys.path.insert(0, {repo!r})
from tools.chunk_dict_profile import profile
print(json.dumps(profile(entries_m=2.0, grow_k=200)))
"""


_PEER_STORM_CHILD = """
import json, sys
sys.path.insert(0, {repo!r})
from tools.cluster_storm_profile import profile
print(json.dumps(profile(pods=8, mib=1, reps=2)))
"""


def peer_storm_run(repo: str, timeout: float = 240.0) -> dict:
    """Cluster deploy-storm profile (tools/cluster_storm_profile.py) in
    a child under the hard watchdog: registry egress ratio (peers on vs
    off), aggregate storm wall + paired best-rep/analytic speedup, and
    the weighted-tenant fairness spread. Dozens of UDS servers and fetch
    pools spin up — a wedge must cost one timeout, not a hang."""
    res = _run_child_watchdog(
        [sys.executable, "-c", _PEER_STORM_CHILD.format(repo=repo)], timeout=timeout
    )
    if res is None:
        return {"error": f"peer storm hung >{timeout:.0f}s (watchdog killed it)"}
    rc, stdout, stderr = res
    if rc != 0:
        tail = stderr.strip().splitlines()[-1] if stderr.strip() else ""
        return {"error": f"peer storm exited rc={rc}: {tail}"[:200]}
    try:
        return json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "peer storm produced no JSON"}


_PEER_TOPOLOGY_CHILD = """
import json, sys
sys.path.insert(0, {repo!r})
from tools.cluster_storm_profile import topology_profile
print(json.dumps(topology_profile(pods=6, mib=2, reps=1)))
"""


def peer_topology_run(repo: str, timeout: float = 300.0) -> dict:
    """Hierarchical rack/zone/region topology profile (the ISSUE 18
    `--topology` arm of tools/cluster_storm_profile.py) in a child under
    the hard watchdog: per-zone origin-egress ratio vs unique bytes,
    hedged-vs-unhedged slow-peer p99 (paired best-rep), and the
    kill-a-zone identity arm. A 3-rack x 2-zone mesh of UDS servers
    spins up — a wedge must cost one timeout, not a hang."""
    res = _run_child_watchdog(
        [sys.executable, "-c", _PEER_TOPOLOGY_CHILD.format(repo=repo)],
        timeout=timeout,
    )
    if res is None:
        return {"error": f"peer topology hung >{timeout:.0f}s (watchdog killed it)"}
    rc, stdout, stderr = res
    if rc != 0:
        tail = stderr.strip().splitlines()[-1] if stderr.strip() else ""
        return {"error": f"peer topology exited rc={rc}: {tail}"[:200]}
    try:
        return json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "peer topology produced no JSON"}


_SOCI_CHILD = """
import json, sys
sys.path.insert(0, {repo!r})
from tools.soci_profile import profile
print(json.dumps(profile(pods=4, mib=4, reps=2)))
"""


def soci_run(repo: str, timeout: float = 300.0) -> dict:
    """Seekable-OCI profile (tools/soci_profile.py) in a child under the
    hard watchdog: index build MiB/s vs the banked stargz_zran line,
    cold first-file-read latency curve vs full pull, and the mini
    indexed-storm origin-egress ratio on unconverted images. Peer UDS
    servers and fetch pools spin up — a wedge costs one timeout."""
    res = _run_child_watchdog(
        [sys.executable, "-c", _SOCI_CHILD.format(repo=repo)], timeout=timeout
    )
    if res is None:
        return {"error": f"soci profile hung >{timeout:.0f}s (watchdog killed it)"}
    rc, stdout, stderr = res
    if rc != 0:
        tail = stderr.strip().splitlines()[-1] if stderr.strip() else ""
        return {"error": f"soci profile exited rc={rc}: {tail}"[:200]}
    try:
        return json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "soci profile produced no JSON"}


_SOCI_FORMATS_CHILD = """
import json, sys
sys.path.insert(0, {repo!r})
from tools.soci_profile import formats_profile
print(json.dumps(formats_profile(pods=4, mib=4, reps=2)))
"""


def soci_formats_run(repo: str, timeout: float = 300.0) -> dict:
    """Universal lazy-format matrix (tools/soci_profile.py --formats) in
    a child under the hard watchdog: per-format byte identity, cold
    first-read ratios (zstd >= 5x), FormatRouter routing, and the
    mini mixed-format storm (TOC adoption at ~zero prepare bytes,
    egress <= 1.05x unique compressed bytes)."""
    res = _run_child_watchdog(
        [sys.executable, "-c", _SOCI_FORMATS_CHILD.format(repo=repo)],
        timeout=timeout,
    )
    if res is None:
        return {"error": f"soci formats hung >{timeout:.0f}s (watchdog killed it)"}
    rc, stdout, stderr = res
    if rc != 0:
        tail = stderr.strip().splitlines()[-1] if stderr.strip() else ""
        return {"error": f"soci formats exited rc={rc}: {tail}"[:200]}
    try:
        return json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "soci formats produced no JSON"}


_FLEET_OBS_CHILD = """
import json, sys
sys.path.insert(0, {repo!r})
from tools.fleet_obs_profile import profile
print(json.dumps(profile(layers=4, pods=4, reps=2)))
"""


def fleet_obs_run(repo: str, timeout: float = 240.0) -> dict:
    """Fleet observability profile (tools/fleet_obs_profile.py) in a
    child under the hard watchdog: federation scrape + trace aggregation
    overhead on a snapshot storm (paired best-rep + duty-cycle bound)
    plus the spawned-member ntpuctl smoke. Two daemon subprocesses and a
    controller spin up — a wedge must cost one timeout, not a hang."""
    res = _run_child_watchdog(
        [sys.executable, "-c", _FLEET_OBS_CHILD.format(repo=repo)], timeout=timeout
    )
    if res is None:
        return {"error": f"fleet obs profile hung >{timeout:.0f}s (watchdog killed it)"}
    rc, stdout, stderr = res
    if rc != 0:
        tail = stderr.strip().splitlines()[-1] if stderr.strip() else ""
        return {"error": f"fleet obs profile exited rc={rc}: {tail}"[:200]}
    try:
        return json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "fleet obs profile produced no JSON"}


def chunk_dict_run(repo: str, timeout: float = 240.0) -> dict:
    """Chunk-dict growth + service profile (tools/chunk_dict_profile.py)
    in a child under the hard watchdog: incremental-vs-rebuild best-rep
    ratio, identity gates, and the DictService round-trip byte-identity.
    A wedged UDS server costs one timeout, not a hang."""
    res = _run_child_watchdog(
        [sys.executable, "-c", _CHUNK_DICT_CHILD.format(repo=repo)], timeout=timeout
    )
    if res is None:
        return {"error": f"chunk-dict profile hung >{timeout:.0f}s (watchdog killed it)"}
    rc, stdout, stderr = res
    if rc != 0:
        tail = stderr.strip().splitlines()[-1] if stderr.strip() else ""
        return {"error": f"chunk-dict profile exited rc={rc}: {tail}"[:200]}
    try:
        return json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "chunk-dict profile produced no JSON"}


_DICT_HA_CHILD = """
import json, sys
sys.path.insert(0, {repo!r})
from tools.dict_ha_profile import profile
print(json.dumps(profile(images=6, files=4, reps=2)))
"""


def dict_ha_run(repo: str, timeout: float = 420.0) -> dict:
    """Dict-shard HA profile (tools/dict_ha_profile.py) in a child under
    the hard watchdog: the 2-shard/1-replica kill-the-primary storm —
    converter byte-identity across a SIGKILL, automatic promotion,
    budget-bounded replica catch-up, and the paired best-rep demand-p95
    gate. Spawns a controller + 4 member processes; a wedge costs one
    timeout, not a hang."""
    res = _run_child_watchdog(
        [sys.executable, "-c", _DICT_HA_CHILD.format(repo=repo)], timeout=timeout
    )
    if res is None:
        return {"error": f"dict-ha profile hung >{timeout:.0f}s (watchdog killed it)"}
    rc, stdout, stderr = res
    if rc != 0:
        tail = stderr.strip().splitlines()[-1] if stderr.strip() else ""
        return {"error": f"dict-ha profile exited rc={rc}: {tail}"[:200]}
    try:
        return json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "dict-ha profile produced no JSON"}


_SOAK_CHILD = """
import json, os, sys
sys.path.insert(0, {repo!r})
from tools.soak_profile import profile
spec = os.path.join({repo!r}, "misc", "scenarios", "soak_smoke.toml")
print(json.dumps(profile(spec, mini=True)))
"""


def soak_run(repo: str, timeout: float = 420.0) -> dict:
    """Mini endurance soak (tools/soak_profile.py --mini over
    soak_smoke.toml) in a child under the hard watchdog: 3 seeded
    arrival epochs with corpus drift, per-epoch audit + leak sentinels,
    one scale-up cycle and serial spot-epoch identity. A wedged epoch
    costs one timeout, not a hang."""
    res = _run_child_watchdog(
        [sys.executable, "-c", _SOAK_CHILD.format(repo=repo)], timeout=timeout
    )
    if res is None:
        return {"error": f"soak profile hung >{timeout:.0f}s (watchdog killed it)"}
    rc, stdout, stderr = res
    if rc != 0:
        tail = stderr.strip().splitlines()[-1] if stderr.strip() else ""
        return {"error": f"soak profile exited rc={rc}: {tail}"[:200]}
    try:
        return json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "soak profile produced no JSON"}


_COMPRESSION_CHILD = """
import json, sys
sys.path.insert(0, {repo!r})
from tools.compression_profile import profile
print(json.dumps(profile(mib=12, reps=2)))
"""


def compression_adaptive_run(repo: str, timeout: float = 240.0) -> dict:
    """Adaptive-codec profile (tools/compression_profile.py) in a child
    under the hard watchdog: paired best-rep + analytic speedup at
    reference defaults, roundtrip identity on every arm, bypass
    discipline, trained-dict loud-failure and DCtx-pool gates. A wedged
    codec costs one timeout, not a hang."""
    res = _run_child_watchdog(
        [sys.executable, "-c", _COMPRESSION_CHILD.format(repo=repo)], timeout=timeout
    )
    if res is None:
        return {"error": f"compression profile hung >{timeout:.0f}s (watchdog killed it)"}
    rc, stdout, stderr = res
    if rc != 0:
        tail = stderr.strip().splitlines()[-1] if stderr.strip() else ""
        return {"error": f"compression profile exited rc={rc}: {tail}"[:200]}
    try:
        return json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "compression profile produced no JSON"}


_VECTORIZED_CHILD = """
import json, sys
sys.path.insert(0, {repo!r})
from tools.compression_profile import batched_profile, vectorized_profile
out = {{}}
try:
    out["scan"] = vectorized_profile(mib=12, reps=2)
except Exception as e:
    out["scan"] = {{"error": str(e)[:200]}}
try:
    out["batch"] = batched_profile(mib=12, reps=3)
except Exception as e:
    out["batch"] = {{"error": str(e)[:200]}}
print(json.dumps(out))
"""


def compression_vectorized_run(repo: str, timeout: float = 240.0) -> dict:
    """Vectorized-scan + batched-lane gates (tools/compression_profile.py
    --vectorized --batched) in a watchdogged child: cut/frame identity
    aborts inside the child, so a diverging kernel surfaces as an error
    row here instead of silently banking a wrong-output speedup."""
    res = _run_child_watchdog(
        [sys.executable, "-c", _VECTORIZED_CHILD.format(repo=repo)],
        timeout=timeout,
    )
    if res is None:
        return {"error": f"vectorized profile hung >{timeout:.0f}s (watchdog killed it)"}
    rc, stdout, stderr = res
    if rc != 0:
        tail = stderr.strip().splitlines()[-1] if stderr.strip() else ""
        return {"error": f"vectorized profile exited rc={rc}: {tail}"[:200]}
    try:
        return json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "vectorized profile produced no JSON"}


def _device_available(repo: str, timeout: float = 120.0) -> tuple[bool, str]:
    """(ok, note) — probe jax.devices() in a subprocess under the hard
    watchdog (_run_child_watchdog): a wedged device tunnel must degrade
    the bench to the host arm CLEANLY, never stall it (BENCH_r05 recorded
    the whole bench wedging behind this probe). The note records WHY the
    device was not engaged so a host-arm result is attributable (wedged
    tunnel vs lost race vs import failure)."""
    child = (
        "import os, sys; os.environ.setdefault('JAX_COMPILATION_CACHE_DIR',"
        " '/tmp/ntpu_jax_cache'); sys.path.insert(0, %r);"
        " import jax; print([d.platform for d in jax.devices()])" % repo
    )
    res = _run_child_watchdog([sys.executable, "-c", child], timeout=timeout)
    if res is None:
        return False, (
            f"device probe hung >{timeout:.0f}s (wedged tunnel; watchdog "
            "SIGKILLed the probe pgroup, bench fell back to host arm)"
        )
    rc, stdout, stderr = res
    if rc == 0 and stdout.strip():
        platforms = stdout.strip().splitlines()[-1]
        if "'cpu'" in platforms and "tpu" not in platforms:
            # jax silently fell back to host CPU: that is NOT a device
            return False, f"jax fell back to CPU-only ({platforms})"
        return True, f"devices: {platforms}"
    err = stderr.strip().splitlines()[-1] if stderr.strip() else ""
    return False, f"device probe exited rc={rc}: {err}"[:200]


def main() -> None:
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/ntpu_jax_cache")
    repo = os.path.dirname(os.path.abspath(__file__))

    from nydus_snapshotter_tpu.converter.types import PackOption
    from nydus_snapshotter_tpu.ops import native_cdc
    from nydus_snapshotter_tpu.ops.chunker import ChunkDigestEngine

    device_ok, device_note = _device_available(repo)
    winner, device_executes, cal, probe_order = calibrate_engine(
        CHUNK_SIZE, repo, device_ok
    )
    if device_ok and not device_executes:
        device_note += "; every device arm failed calibration"
    elif device_ok and winner == "host":
        device_note += "; device arms lost the end-to-end race"
    device_ok = device_ok and device_executes

    bench_engine = ChunkDigestEngine(
        chunk_size=CHUNK_SIZE, mode="cdc", **ENGINE_ARMS[winner]
    )
    fused = bench_engine._fused_available()
    if bench_engine.backend == "jax":
        from nydus_snapshotter_tpu.ops import gear_pallas

        gear_kernel = "pallas" if gear_pallas.supported(bench_engine.window) else "xla"
    elif fused:
        gear_kernel = "host-fused"
    elif native_cdc.available():
        gear_kernel = "host-native"
    else:
        gear_kernel = "host-numpy"

    # Probe warm-up dict (also forces compilation of probe shapes).
    warm_metas = bench_engine.process_many(build_corpus(CALIBRATE_MIB, 2))
    warm_digest_bytes = b"".join(m.digest for metas in warm_metas for m in metas)
    probe, probe_arm = build_probe(warm_digest_bytes, device_ok)
    if winner != "host":
        bench_engine.process_many(build_corpus(CORPUS_MIB, N_FILES))  # shapes

    # ---- headline: full-path convert of the node-shaped image ----
    opt = PackOption(chunk_size=CHUNK_SIZE, chunking="cdc", **_pack_kwargs(winner))
    layers, corpus_info = build_node_shaped_layers(IMAGE_MIB, seed=7)
    full_gibps, blobs, results, stage_breakdown, pipeline_info = full_path_run(
        layers, opt
    )
    comp_bytes = sum(r.blob_size for r in results)
    corpus_info["compress_ratio"] = round(
        comp_bytes / max(1, sum(len(t) for t in layers)), 4
    )

    # Speed-profile arm: same full path with the documented lz4
    # acceleration dial (PackOption.lz4_acceleration=8). The headline
    # stays at fidelity defaults; this records what the knob buys and
    # what ratio it costs on the same corpus.
    opt_accel = PackOption(
        chunk_size=CHUNK_SIZE, chunking="cdc", lz4_acceleration=8,
        **_pack_kwargs(winner),
    )
    total_in = sum(len(t) for t in layers)
    accel_best = None
    packed_accel = None
    for _ in range(REPS):  # same best-of-REPS discipline as the headline
        t0 = time.time()
        packed_accel = _pack_layers(layers, opt_accel)
        dt = time.time() - t0
        accel_best = dt if accel_best is None or dt < accel_best else accel_best
    accel_profile = {
        "lz4_acceleration": 8,
        "full_path_gibps": round(total_in / accel_best / (1 << 30), 4),
        "compress_ratio": round(
            sum(r.blob_size for _b, r in packed_accel) / max(1, total_in), 4
        ),
    }

    # zstd arm: same full path at the reference toolchain's modern default
    # compressor (native fused section assembly via the system libzstd,
    # level constants.ZSTD_LEVEL) — records the speed/ratio tradeoff vs
    # the lz4 headline on the same corpus.
    opt_zstd = PackOption(
        chunk_size=CHUNK_SIZE, chunking="cdc", compressor="zstd",
        **_pack_kwargs(winner),
    )
    zstd_best = None
    packed_zstd = None
    for _ in range(REPS):
        t0 = time.time()
        packed_zstd = _pack_layers(layers, opt_zstd)
        dt = time.time() - t0
        zstd_best = dt if zstd_best is None or dt < zstd_best else zstd_best
    from nydus_snapshotter_tpu import constants as _const

    zstd_profile = {
        "level": _const.ZSTD_LEVEL,
        "full_path_gibps": round(total_in / zstd_best / (1 << 30), 4),
        "compress_ratio": round(
            sum(r.blob_size for _b, r in packed_zstd) / max(1, total_in), 4
        ),
    }

    # Reference-defaults arm: the real nydus-image defaults are blake3
    # chunk digests + zstd — the configuration whose output interops with
    # real nydus images (chunk-dict content hits are digest-keyed). The
    # blake3 digests ride the same fused native pass (8-way AVX2 leaves).
    opt_refdef = PackOption(
        chunk_size=CHUNK_SIZE, chunking="cdc", compressor="zstd",
        digester="blake3", **_pack_kwargs(winner),
    )
    refdef_best = None
    packed_refdef = None
    for _ in range(REPS):
        t0 = time.time()
        packed_refdef = _pack_layers(layers, opt_refdef)
        dt = time.time() - t0
        refdef_best = dt if refdef_best is None or dt < refdef_best else refdef_best
    reference_defaults_profile = {
        "digester": "blake3",
        "compressor": "zstd",
        "full_path_gibps": round(total_in / refdef_best / (1 << 30), 4),
        "compress_ratio": round(
            sum(r.blob_size for _b, r in packed_refdef) / max(1, total_in), 4
        ),
    }

    # Uncompressed arm + derived codec economics: the denominator for the
    # compression scaling argument (docs/COMPRESSION_SCALING.md). The
    # per-core codec rate is derived from the measured wall deltas on the
    # ACTUAL corpus (unique post-dedup bytes / extra wall vs "none"), so
    # each round re-grounds the cores-needed-for-20GiB/s table on the
    # bench box rather than trusting the doc's frozen numbers.
    opt_none = PackOption(
        chunk_size=CHUNK_SIZE, chunking="cdc", compressor="none",
        **_pack_kwargs(winner),
    )
    none_best = None
    packed_none = None
    for _ in range(REPS):
        t0 = time.time()
        packed_none = _pack_layers(layers, opt_none)
        dt = time.time() - t0
        none_best = dt if none_best is None or dt < none_best else none_best
    uniq_bytes = sum(r.blob_size for _b, r in packed_none)  # raw unique
    ncores = os.cpu_count() or 1

    # Per-core codec rates need SERIAL walls: _pack_layers runs layers on
    # a thread pool, so on a multi-core box its wall deltas would reflect
    # N cores compressing concurrently and overstate the per-core rate.
    def _serial_wall(o):
        best = None
        for _ in range(REPS):
            t0 = time.time()
            for t in layers:
                pack_layer_fn(t, o)
            dt = time.time() - t0
            best = dt if best is None or dt < best else best
        return best

    from nydus_snapshotter_tpu.converter.convert import (
        pack_layer as pack_layer_fn,
    )

    none_serial = _serial_wall(opt_none)
    lz4_serial = _serial_wall(opt)
    zstd_serial = _serial_wall(opt_zstd)

    def _codec_rate(wall):
        # unique bytes compressed during (wall - uncompressed wall);
        # None when the delta is within noise (a codec wall at or below
        # the uncompressed wall) rather than an absurd clamped rate
        extra = wall - none_serial
        if extra <= 0.01 * none_serial:
            return None
        return uniq_bytes / extra / (1 << 30)

    target = PER_CHIP_TARGET_GIBPS * 8  # 20 GiB/s aggregate
    uniq_frac = uniq_bytes / max(1, total_in)
    lz4_rate = _codec_rate(lz4_serial)
    zstd_rate = _codec_rate(zstd_serial)
    compression_economics = {
        "uncompressed_full_path_gibps": round(
            total_in / none_best / (1 << 30), 4
        ),
        "unique_fraction_post_dedup": round(uniq_frac, 4),
        "lz4_gibps_per_core": round(lz4_rate, 4) if lz4_rate else None,
        "zstd_gibps_per_core": round(zstd_rate, 4) if zstd_rate else None,
        "cores_for_20gibps_lz4": (
            round(target * uniq_frac / lz4_rate, 1) if lz4_rate else None
        ),
        "cores_for_20gibps_zstd": (
            round(target * uniq_frac / zstd_rate, 1) if zstd_rate else None
        ),
        "refdef_vs_uncompressed": round(
            reference_defaults_profile["full_path_gibps"]
            / max(1e-9, total_in / none_best / (1 << 30)),
            4,
        ),
        "overlap_note": (
            "per-chunk frames are independent; compression scales across "
            f"cores and pipelines behind chunk+digest — this box has "
            f"{ncores} core(s), so walls here are fully serialized"
        ),
    }

    # ---- detail runs ----
    engine_detail = engine_flat_run(bench_engine, probe)
    pool = build_file_pool(min(IMAGE_MIB, 128), seed=555)
    shaped = dedup_shaped_run(opt, pool)
    stargz_zran = stargz_zran_run(opt)
    real_image = real_image_run(opt)
    lazy_read = lazy_read_run(repo)
    snapshot_ops = snapshot_ops_run(repo)
    trace_detail = trace_run(repo)
    chunk_dict_detail = chunk_dict_run(repo)
    dict_ha_detail = dict_ha_run(repo)
    soak_detail = soak_run(repo)
    peer_storm = peer_storm_run(repo)
    peer_topology = peer_topology_run(repo)
    fleet_obs = fleet_obs_run(repo)
    soci_detail = soci_run(repo)
    soci_detail["formats"] = soci_formats_run(repo)
    # Adaptive-codec engine numbers ride under detail.compression next
    # to the per-codec economics they change.
    compression_economics["adaptive"] = compression_adaptive_run(repo)
    # Vectorized scan + batched codec lane: identity-gated best-rep
    # ratios and ns/byte bounds for the two compression-wall kernels.
    compression_economics["vectorized"] = compression_vectorized_run(repo)

    print(
        json.dumps(
            {
                "metric": "rafs_convert_full_path_per_chip",
                "value": round(full_gibps, 4),
                "unit": "GiB/s",
                "vs_baseline": round(full_gibps / PER_CHIP_TARGET_GIBPS, 4),
                "detail": {
                    "metric_note": (
                        "headline switched r3 from bare engine to FULL-PATH "
                        "convert (VERDICT r2 next #2); engine_flat.engine_gibps "
                        "is the series comparable to r1/r2 values"
                    ),
                    "image_mib": IMAGE_MIB,
                    "chunk_size": CHUNK_SIZE,
                    "compressor": opt.compressor,
                    "corpus": corpus_info,
                    "engine_arm": winner,
                    "digest_backend": opt.digest_backend
                    or bench_engine.digest_backend,
                    "gear_kernel": gear_kernel,
                    "probe_arm": probe_arm,
                    "device": device_ok,
                    "device_note": device_note,
                    # order device children were dispatched into the
                    # tunnel window: the full-path fused arm MUST be
                    # first (VERDICT r5); empty when no window opened
                    "device_probe_order": probe_order,
                    "calibration": cal,
                    "engine_flat": engine_detail,
                    "stage_breakdown_s": stage_breakdown,
                    "pipeline": pipeline_info,
                    "lazy_read": lazy_read,
                    "snapshot_ops": snapshot_ops,
                    "trace": trace_detail,
                    "chunk_dict": chunk_dict_detail,
                    "dict_ha": dict_ha_detail,
                    "soak": soak_detail,
                    "peer_storm": peer_storm,
                    "peer_topology": peer_topology,
                    "fleet_obs": fleet_obs,
                    "soci": soci_detail,
                    "accel_profile": accel_profile,
                    "zstd_profile": zstd_profile,
                    "reference_defaults_profile": reference_defaults_profile,
                    "compression": compression_economics,
                    "baseline_shaped": shaped,
                    "real_image": real_image,
                    "stargz_zran": stargz_zran,
                    "host_cores": os.cpu_count(),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.path.insert(0, ".")
    main()
