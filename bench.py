"""Conversion data-plane benchmark on the real TPU chip.

Measures the accel hot path the BASELINE targets (RAFS convert GiB/s/chip):
content-defined chunking + SHA-256 chunk digesting + chunk-dict dedup probe
over a synthetic layer corpus (mixed random/duplicated content, like the
reference smoke corpus, tests/converter_test.go:177-225).

Engine selection is measured, not assumed (SURVEY §7 hard-part #3):

- **Boundaries**: the Pallas gear-bitmap kernel (ops/gear_pallas.py —
  gather-free mix32 + log-doubling window sum in VMEM) when a TPU answers,
  else the native C++ chunker / numpy windowed fallback.
- **Digests**: host (threaded hashlib) vs device (bucketed uint32-lane
  SHA-256) raced on a calibration slice; winner takes the corpus.
- **Dict probe**: native C++ open-addressing probe on a single chip (XLA
  TPU gathers are element-serial, measured ~1 µs/element), the sharded
  all_to_all path on multi-chip meshes.

Prints ONE JSON line: metric, value (GiB/s on this chip), unit, vs_baseline
(fraction of the 2.5 GiB/s per-chip share of the 20 GiB/s v5e-8 target),
and a per-stage breakdown (boundaries / digest / probe wall seconds) so a
regression is attributable to a stage, not vibes.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

PER_CHIP_TARGET_GIBPS = 20.0 / 8.0  # north-star 20 GiB/s on a v5e-8

CORPUS_MIB = int(os.environ.get("NTPU_BENCH_MIB", "384"))
CHUNK_SIZE = 0x10000  # 64 KiB average: matches dedup-grade chunking
N_FILES = 24
CALIBRATE_MIB = 16
REPS = 3


def build_corpus(total_mib: int, n_files: int) -> list[bytes]:
    rng = np.random.default_rng(42)
    per = total_mib * (1 << 20) // n_files
    base = rng.integers(0, 256, per, dtype=np.uint8).tobytes()
    files = []
    for i in range(n_files):
        if i % 3 == 2:
            files.append(base)  # duplicated content: dedup work is real
        else:
            files.append(rng.integers(0, 256, per, dtype=np.uint8).tobytes())
    return files


_ENGINE_CHILD = """
import os, sys, time
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/ntpu_jax_cache")
sys.path.insert(0, {repo!r})
import numpy as np
from nydus_snapshotter_tpu.ops.chunker import ChunkDigestEngine
rng = np.random.default_rng(7)
sample = [rng.integers(0, 256, {mib} << 19, dtype=np.uint8).tobytes() for _ in range(2)]
eng = ChunkDigestEngine(chunk_size={chunk_size}, mode="cdc", **{kwargs!r})
eng.process_many(sample)  # compile warm-up
t = time.time()
eng.process_many(sample)
print(time.time() - t)
"""

# Candidate engine arms raced end-to-end (process_many on the calibration
# slice). "host" runs in-process; device arms run in a SUBPROCESS with a
# hard timeout so a hostile backend (slow compile, wedged device tunnel)
# loses the race instead of hanging the bench — the persistent JAX compile
# cache carries the child's compilation over to the real run. Racing full
# pipelines (not isolated stages) is what keeps the pick honest: the host
# arm may be a single fused chunk+digest pass, which a stage-wise race
# would never credit.
ENGINE_ARMS = {
    "host": {"backend": "hybrid"},
    "device_digest": {"backend": "hybrid", "digest_backend": "jax"},
    "device_all": {"backend": "jax", "digest_backend": "jax"},
}


def _time_engine_child(repo: str, chunk_size: int, kwargs: dict):
    """Timed process_many in a subprocess; None on failure/timeout."""
    import subprocess

    child = _ENGINE_CHILD.format(
        repo=repo, mib=CALIBRATE_MIB, chunk_size=chunk_size, kwargs=kwargs
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", child], capture_output=True, text=True, timeout=240,
        )
        if out.returncode != 0:
            return None
        return float(out.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, ValueError, IndexError):
        return None


def calibrate_engine(chunk_size: int, repo: str, device_ok: bool):
    """(winning arm name, device_executes, timings) from the end-to-end
    race. ``device_executes`` is False when every device arm failed
    outright (not merely lost) — the device must then not be used for
    anything, including the dict probe."""
    from nydus_snapshotter_tpu.ops.chunker import ChunkDigestEngine

    rng = np.random.default_rng(7)
    sample = [rng.integers(0, 256, CALIBRATE_MIB << 19, dtype=np.uint8).tobytes()
              for _ in range(2)]
    host = ChunkDigestEngine(chunk_size=chunk_size, mode="cdc", **ENGINE_ARMS["host"])
    host.process_many(sample)  # thread-pool / build warm-up
    t = time.time()
    host.process_many(sample)
    times = {"host": time.time() - t}

    if device_ok:
        for arm in ("device_digest", "device_all"):
            dt = _time_engine_child(repo, chunk_size, ENGINE_ARMS[arm])
            if dt is not None:
                times[arm] = dt
    winner = min(times, key=times.get)
    device_executes = any(k != "host" for k in times)
    return winner, device_executes, {k: round(v, 3) for k, v in times.items()}


def build_probe(dict_digest_bytes: bytes, device_ok: bool):
    """(probe fn, arm name) for a chunk dict of raw 32-byte digests.

    Probe arm: native host table on one chip (device gathers are
    element-serial), sharded all_to_all on real meshes; pure-python set as
    the last resort. Never touches jax backend init unless the device
    already answered (a wedged tunnel must not hang the bench).
    """
    from nydus_snapshotter_tpu.ops import native_cdc
    from nydus_snapshotter_tpu.parallel import mesh as mesh_lib
    from nydus_snapshotter_tpu.parallel.sharded_dict import ShardedChunkDict

    dict_digests = (
        np.frombuffer(dict_digest_bytes, dtype="<u4").reshape(-1, 8)
        if dict_digest_bytes
        else np.zeros((0, 8), np.uint32)
    )
    if device_ok:
        sdict = ShardedChunkDict(dict_digests, mesh_lib.make_mesh(1))
        sdict.lookup_digests([dict_digest_bytes[:32]] if dict_digest_bytes else [])
        return sdict.lookup_digests, (
            "host-native" if sdict._use_host_probe() else "device"
        )
    if native_cdc.dict_probe_available():
        from nydus_snapshotter_tpu.parallel.sharded_dict import (
            MAX_PROBE,
            _build_host_tables,
        )

        keys, values = _build_host_tables(dict_digests, 1)

        def probe(digests):
            q = np.frombuffer(b"".join(digests), dtype="<u4").reshape(-1, 8)
            return native_cdc.dict_probe_native(
                q, keys.reshape(-1, 8), values.reshape(-1), 1, keys.shape[1], MAX_PROBE
            )

        return probe, "host-native"

    dict_set = {
        dict_digest_bytes[i : i + 32] for i in range(0, len(dict_digest_bytes), 32)
    }
    return (lambda digests: np.asarray([d in dict_set for d in digests])), "host-set"


def build_layered_images(total_mib: int):
    """Two synthetic multi-layer images with real cross-image overlap —
    the BASELINE config #2/#3 shape (node:21-with-chunk-dict, batch vs
    shared dict) without network access. Image A is the dict source;
    image B re-uses ~half of A's content blocks, so dedup hits are
    meaningful, not incidental."""
    rng = np.random.default_rng(1234)
    n_layers = 6
    per_image = total_mib * (1 << 20) // 2
    # log-spread layer sizes like real images (one big rootfs layer, small
    # config/app layers), normalized to per_image bytes
    weights = np.asarray([32.0, 16.0, 8.0, 4.0, 2.0, 2.0])
    sizes = (weights / weights.sum() * per_image).astype(np.int64)
    pool = rng.integers(0, 256, per_image, dtype=np.uint8)  # shared content pool

    def make_layers(reuse_fraction: float) -> list[bytes]:
        layers = []
        for s in sizes:
            n_reuse = int(s * reuse_fraction)
            fresh = rng.integers(0, 256, s - n_reuse, dtype=np.uint8)
            off = int(rng.integers(0, max(1, pool.size - n_reuse)))
            layers.append(
                np.concatenate([pool[off : off + n_reuse], fresh]).tobytes()
            )
        return layers

    return make_layers(1.0), make_layers(0.5)


def baseline_shaped_run(engine, device_ok: bool) -> dict:
    """Convert image A (builds the chunk dict), then image B against it;
    report per-image engine throughput and the measured dedup ratio."""
    image_a, image_b = build_layered_images(total_mib=min(CORPUS_MIB, 256))

    warm_digests_b = None
    if engine.backend == "jax" or engine.digest_backend == "jax":
        # Device arms compile per shape; the layered sizes are new shapes,
        # so warm them (and the probe batch, below) outside the timers or
        # the numbers measure XLA compilation, not conversion.
        engine.process_many(image_a)
        warm_b = engine.process_many(image_b)
        warm_digests_b = [m.digest for layer in warm_b for m in layer]

    t0 = time.time()
    metas_a = engine.process_many(image_a)
    t_a = time.time() - t0
    dict_bytes = b"".join(m.digest for layer in metas_a for m in layer)
    probe, _arm = build_probe(dict_bytes, device_ok)
    if warm_digests_b is not None:
        probe(warm_digests_b)  # compile the probe's real batch shape

    t1 = time.time()
    metas_b = engine.process_many(image_b)
    flat_b = [m.digest for layer in metas_b for m in layer]
    hits = np.asarray(probe(flat_b))
    t_b = time.time() - t1

    bytes_a = sum(len(x) for x in image_a)
    bytes_b = sum(len(x) for x in image_b)
    hit_mask = hits if hits.dtype == bool else hits >= 0
    sizes_b = np.asarray([m.size for layer in metas_b for m in layer])
    dedup_bytes = int(sizes_b[hit_mask].sum())
    return {
        "image_mib": round(bytes_a / (1 << 20)),
        "layers": len(image_a),
        "dict_chunks": len(dict_bytes) // 32,
        "build_dict_gibps": round(bytes_a / t_a / (1 << 30), 4),
        "convert_vs_dict_gibps": round(bytes_b / t_b / (1 << 30), 4),
        "dedup_ratio": round(dedup_bytes / bytes_b, 4),
    }


def _device_available(repo: str, timeout: float = 120.0) -> tuple[bool, str]:
    """(ok, note) — probe jax.devices() in a subprocess: a wedged device
    tunnel must degrade the bench to the host arm, not hang it. The note
    records WHY the device was not engaged so a host-arm result is
    attributable (wedged tunnel vs lost race vs import failure)."""
    import subprocess

    child = (
        "import os, sys; os.environ.setdefault('JAX_COMPILATION_CACHE_DIR',"
        " '/tmp/ntpu_jax_cache'); sys.path.insert(0, %r);"
        " import jax; print([d.platform for d in jax.devices()])" % repo
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", child], capture_output=True, text=True,
            timeout=timeout,
        )
        if out.returncode == 0 and out.stdout.strip():
            platforms = out.stdout.strip().splitlines()[-1]
            if "'cpu'" in platforms and "tpu" not in platforms:
                # jax silently fell back to host CPU: that is NOT a device
                return False, f"jax fell back to CPU-only ({platforms})"
            return True, f"devices: {platforms}"
        err = out.stderr.strip().splitlines()[-1] if out.stderr.strip() else ""
        return False, f"device probe exited rc={out.returncode}: {err}"[:200]
    except subprocess.TimeoutExpired:
        return False, f"device probe hung >{timeout:.0f}s (wedged tunnel)"


def main() -> None:
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/ntpu_jax_cache")
    repo = os.path.dirname(os.path.abspath(__file__))

    from nydus_snapshotter_tpu.ops import native_cdc
    from nydus_snapshotter_tpu.ops.chunker import ChunkDigestEngine

    files = build_corpus(CORPUS_MIB, N_FILES)
    total_bytes = sum(len(f) for f in files)

    device_ok, device_note = _device_available(repo)
    winner, device_executes, cal = calibrate_engine(CHUNK_SIZE, repo, device_ok)
    if device_ok and not device_executes:
        device_note += "; every device arm failed calibration"
    elif device_ok and winner == "host":
        device_note += "; device arms lost the end-to-end race"
    device_ok = device_ok and device_executes
    bench_engine = ChunkDigestEngine(
        chunk_size=CHUNK_SIZE, mode="cdc", **ENGINE_ARMS[winner]
    )
    engine = (
        bench_engine
        if winner == "host"
        else ChunkDigestEngine(chunk_size=CHUNK_SIZE, mode="cdc", backend="hybrid")
    )
    digest_backend = bench_engine.digest_backend

    if bench_engine.backend == "jax":
        from nydus_snapshotter_tpu.ops import gear_pallas

        gear_kernel = "pallas" if gear_pallas.supported(bench_engine.window) else "xla"
    elif native_cdc.available():
        gear_kernel = "host-native"
    else:
        gear_kernel = "host-numpy"

    # Build the chunk dict from a warm-up slice and force compilation of
    # the probe before timing. Probe arm: native host table on one chip
    # (device gathers are element-serial), sharded all_to_all on meshes.
    warm_metas = engine.process_many(build_corpus(CALIBRATE_MIB, 2))
    warm_digest_bytes = b"".join(m.digest for metas in warm_metas for m in metas)
    probe, probe_arm = build_probe(warm_digest_bytes, device_ok)

    if winner != "host":
        # Warm every compiled shape before timing (host arms have nothing
        # to compile; best-of-REPS absorbs their cache warm-up).
        bench_engine.process_many(files)

    from nydus_snapshotter_tpu.ops import cdc

    fused = bench_engine._fused_available()
    best = None
    for _ in range(REPS):
        t0 = time.time()
        arrs = [np.frombuffer(f, dtype=np.uint8) for f in files]
        if fused:
            # Single-pass native arm: boundaries + digests in one sweep
            # (SIMD gear bitmaps + SHA-NI, chunk bytes digested cache-warm).
            t_b0 = time.time()
            metas = bench_engine.process_many(arrs)
            all_digests = [m.digest for f in metas for m in f]
            t_boundaries = time.time() - t_b0
            t_digest = 0.0
        else:
            t_b0 = time.time()
            all_cuts = bench_engine.boundaries_many(arrs)
            t_boundaries = time.time() - t_b0
            t_d0 = time.time()
            per_file_extents = [cdc.cuts_to_extents(c) for c in all_cuts]
            all_digests = bench_engine.digest_all(arrs, per_file_extents)
            t_digest = time.time() - t_d0

        t_p0 = time.time()
        hits = np.asarray(probe(all_digests))  # one batched probe
        t_probe = time.time() - t_p0
        elapsed = time.time() - t0
        n_hits = int(hits.sum() if hits.dtype == bool else (hits >= 0).sum())
        if best is None or elapsed < best["elapsed"]:
            best = {
                "elapsed": elapsed,
                "boundaries_s": t_boundaries,
                "digest_s": t_digest,
                "probe_s": t_probe,
                "n_chunks": len(all_digests),
                "hits": n_hits,
            }

    # BASELINE-shaped slice: layered image pair with cross-image dict
    # dedup (configs #2/#3) — reported alongside the flat-corpus metric.
    shaped = baseline_shaped_run(bench_engine, device_ok)

    gibps = total_bytes / best["elapsed"] / (1 << 30)
    print(
        json.dumps(
            {
                "metric": "rafs_convert_throughput_per_chip",
                "value": round(gibps, 4),
                "unit": "GiB/s",
                "vs_baseline": round(gibps / PER_CHIP_TARGET_GIBPS, 4),
                "detail": {
                    "corpus_mib": CORPUS_MIB,
                    "chunk_size": CHUNK_SIZE,
                    "n_chunks": best["n_chunks"],
                    "dict_hits": best["hits"],
                    "engine_arm": winner,
                    "digest_backend": digest_backend,
                    "gear_kernel": "host-fused" if fused else gear_kernel,
                    "probe_arm": probe_arm,
                    "device": device_ok,
                    "device_note": device_note,
                    "elapsed_s": round(best["elapsed"], 3),
                    "stages_s": (
                        {
                            "chunk_digest": round(best["boundaries_s"], 3),
                            "probe": round(best["probe_s"], 3),
                        }
                        if fused
                        else {
                            "boundaries": round(best["boundaries_s"], 3),
                            "digest": round(best["digest_s"], 3),
                            "probe": round(best["probe_s"], 3),
                        }
                    ),
                    "calibration": cal,
                    "baseline_shaped": shaped,
                },
            }
        )
    )


if __name__ == "__main__":
    sys.path.insert(0, ".")
    main()
