"""Conversion data-plane benchmark on the real TPU chip.

Measures the accel hot path the BASELINE targets (RAFS convert GiB/s/chip):
content-defined chunking + SHA-256 chunk digesting + chunk-dict dedup probe
over a synthetic layer corpus (mixed random/duplicated content, like the
reference smoke corpus, tests/converter_test.go:177-225).

Prints ONE JSON line: metric, value (GiB/s on this chip), unit, vs_baseline
(fraction of the 2.5 GiB/s per-chip share of the 20 GiB/s v5e-8 target).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

PER_CHIP_TARGET_GIBPS = 20.0 / 8.0  # north-star 20 GiB/s on a v5e-8

CORPUS_MIB = 192
CHUNK_SIZE = 0x10000  # 64 KiB average: matches dedup-grade chunking
N_FILES = 24
WARMUP_MIB = 16


def build_corpus(total_mib: int, n_files: int) -> list[bytes]:
    rng = np.random.default_rng(42)
    per = total_mib * (1 << 20) // n_files
    base = rng.integers(0, 256, per, dtype=np.uint8).tobytes()
    files = []
    for i in range(n_files):
        if i % 3 == 2:
            files.append(base)  # duplicated content: dedup work is real
        else:
            files.append(rng.integers(0, 256, per, dtype=np.uint8).tobytes())
    return files


def main() -> None:
    from nydus_snapshotter_tpu.ops.chunker import ChunkDigestEngine
    from nydus_snapshotter_tpu.parallel import mesh as mesh_lib
    from nydus_snapshotter_tpu.parallel.sharded_dict import ShardedChunkDict

    engine = ChunkDigestEngine(chunk_size=CHUNK_SIZE, mode="cdc", backend="jax")
    files = build_corpus(CORPUS_MIB, N_FILES)
    total_bytes = sum(len(f) for f in files)

    # Warm-up: compile every kernel shape on a small slice.
    warm = build_corpus(WARMUP_MIB, 2)
    warm_metas = engine.process_many(warm)
    mesh = mesh_lib.make_mesh(1)
    dict_digests = np.frombuffer(
        b"".join(m.digest for metas in warm_metas for m in metas), dtype="<u4"
    ).reshape(-1, 8)
    sdict = ShardedChunkDict(dict_digests, mesh)
    sdict.lookup_u32(dict_digests[: max(1, len(dict_digests) // 2)])

    t0 = time.time()
    metas = engine.process_many(files)
    all_digests = [m.digest for file_metas in metas for m in file_metas]
    hits = sdict.lookup_digests(all_digests)
    elapsed = time.time() - t0

    n_chunks = len(all_digests)
    gibps = total_bytes / elapsed / (1 << 30)
    print(
        json.dumps(
            {
                "metric": "rafs_convert_throughput_per_chip",
                "value": round(gibps, 4),
                "unit": "GiB/s",
                "vs_baseline": round(gibps / PER_CHIP_TARGET_GIBPS, 4),
                "detail": {
                    "corpus_mib": CORPUS_MIB,
                    "chunk_size": CHUNK_SIZE,
                    "n_chunks": n_chunks,
                    "dict_probes": int(len(hits)),
                    "elapsed_s": round(elapsed, 2),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.path.insert(0, ".")
    main()
