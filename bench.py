"""Conversion data-plane benchmark on the real TPU chip.

Measures the accel hot path the BASELINE targets (RAFS convert GiB/s/chip):
content-defined chunking + SHA-256 chunk digesting + chunk-dict dedup probe
over a synthetic layer corpus (mixed random/duplicated content, like the
reference smoke corpus, tests/converter_test.go:177-225).

The engine is a crossover hybrid (SURVEY §7 hard-part #3): native C++
chunker + host SHA on the latency arm, device kernels on the batch arm; a
short calibration pass picks the digest backend, and the HBM chunk-dict
probe always runs on device in one batched launch.

Prints ONE JSON line: metric, value (GiB/s on this chip), unit, vs_baseline
(fraction of the 2.5 GiB/s per-chip share of the 20 GiB/s v5e-8 target).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

PER_CHIP_TARGET_GIBPS = 20.0 / 8.0  # north-star 20 GiB/s on a v5e-8

CORPUS_MIB = 192
CHUNK_SIZE = 0x10000  # 64 KiB average: matches dedup-grade chunking
N_FILES = 24
CALIBRATE_MIB = 16


def build_corpus(total_mib: int, n_files: int) -> list[bytes]:
    rng = np.random.default_rng(42)
    per = total_mib * (1 << 20) // n_files
    base = rng.integers(0, 256, per, dtype=np.uint8).tobytes()
    files = []
    for i in range(n_files):
        if i % 3 == 2:
            files.append(base)  # duplicated content: dedup work is real
        else:
            files.append(rng.integers(0, 256, per, dtype=np.uint8).tobytes())
    return files


_CALIBRATION_CHILD = """
import os, sys, time
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/ntpu_jax_cache")
sys.path.insert(0, {repo!r})
import numpy as np
from nydus_snapshotter_tpu.ops.chunker import ChunkDigestEngine
rng = np.random.default_rng(7)
sample = [rng.integers(0, 256, {mib} << 19, dtype=np.uint8).tobytes() for _ in range(2)]
dev = ChunkDigestEngine(chunk_size={chunk_size}, mode="cdc", backend="hybrid",
                        digest_backend="jax")
dev.process_many(sample)  # compile warm-up
t = time.time()
dev.process_many(sample)
print(time.time() - t)
"""


def calibrate_digest_backend(
    engine_cls, chunk_size: int, repo: str
) -> tuple[str, bool]:
    """(digest backend, device_executes) — race host vs device digesting on
    a small slice. The device probe runs in a SUBPROCESS with a hard
    timeout so a hostile backend (slow compile, wedged device tunnel)
    degrades to the host arm instead of hanging the bench; the persistent
    JAX compile cache carries the child's compilation over to this process.
    ``device_executes`` is False when the probe failed outright (not merely
    lost the race) — the device must then not be used for anything."""
    import subprocess

    rng = np.random.default_rng(7)
    sample = [rng.integers(0, 256, CALIBRATE_MIB << 19, dtype=np.uint8).tobytes()
              for _ in range(2)]
    host = engine_cls(chunk_size=chunk_size, mode="cdc", backend="hybrid")
    host.process_many(sample)  # thread-pool warm-up
    t = time.time()
    host.process_many(sample)
    host_t = time.time() - t

    child = _CALIBRATION_CHILD.format(repo=repo, mib=CALIBRATE_MIB, chunk_size=chunk_size)
    try:
        out = subprocess.run(
            [sys.executable, "-c", child], capture_output=True, text=True, timeout=240,
        )
        if out.returncode != 0:
            return "host", False
        dev_t = float(out.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, ValueError, IndexError):
        return "host", False
    return ("jax" if dev_t < host_t else "host"), True


def _device_available(repo: str, timeout: float = 120.0) -> bool:
    """Probe jax.devices() in a subprocess: a wedged device tunnel must
    degrade the bench to the host arm, not hang it."""
    import subprocess

    child = (
        "import os, sys; os.environ.setdefault('JAX_COMPILATION_CACHE_DIR',"
        " '/tmp/ntpu_jax_cache'); sys.path.insert(0, %r);"
        " import jax; jax.devices(); print('ok')" % repo
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", child], capture_output=True, text=True,
            timeout=timeout,
        )
        return out.returncode == 0 and "ok" in out.stdout
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    import os

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/ntpu_jax_cache")
    repo = os.path.dirname(os.path.abspath(__file__))

    from nydus_snapshotter_tpu.ops.chunker import ChunkDigestEngine
    from nydus_snapshotter_tpu.parallel import mesh as mesh_lib
    from nydus_snapshotter_tpu.parallel.sharded_dict import ShardedChunkDict

    files = build_corpus(CORPUS_MIB, N_FILES)
    total_bytes = sum(len(f) for f in files)

    device_ok = _device_available(repo)
    if device_ok:
        digest_backend, device_ok = calibrate_digest_backend(
            ChunkDigestEngine, CHUNK_SIZE, repo
        )
    else:
        digest_backend = "host"
    engine = ChunkDigestEngine(
        chunk_size=CHUNK_SIZE, mode="cdc", backend="hybrid",
        digest_backend=digest_backend,
    )

    # Build the chunk dict from a warm-up slice and force compilation of
    # the probe before timing. Device-resident (HBM, one batched launch)
    # when a device answers; host hash-set otherwise.
    warm_metas = engine.process_many(build_corpus(CALIBRATE_MIB, 2))
    warm_digest_bytes = b"".join(m.digest for metas in warm_metas for m in metas)
    if device_ok:
        mesh = mesh_lib.make_mesh(1)
        dict_digests = np.frombuffer(warm_digest_bytes, dtype="<u4").reshape(-1, 8)
        sdict = ShardedChunkDict(dict_digests, mesh)
        sdict.lookup_u32(dict_digests[: max(1, len(dict_digests) // 2)])
        probe = sdict.lookup_digests
    else:
        dict_set = {warm_digest_bytes[i : i + 32] for i in range(0, len(warm_digest_bytes), 32)}

        def probe(digests):
            return np.asarray([d in dict_set for d in digests])

    if digest_backend == "jax":
        # compile the full-corpus global-batch shapes before timing
        engine.process_many(files)

    t0 = time.time()
    metas = engine.process_many(files)
    all_digests = [m.digest for file_metas in metas for m in file_metas]
    hits = probe(all_digests)  # one batched probe
    elapsed = time.time() - t0

    n_chunks = len(all_digests)
    gibps = total_bytes / elapsed / (1 << 30)
    print(
        json.dumps(
            {
                "metric": "rafs_convert_throughput_per_chip",
                "value": round(gibps, 4),
                "unit": "GiB/s",
                "vs_baseline": round(gibps / PER_CHIP_TARGET_GIBPS, 4),
                "detail": {
                    "corpus_mib": CORPUS_MIB,
                    "chunk_size": CHUNK_SIZE,
                    "n_chunks": n_chunks,
                    "dict_probes": int(len(hits)),
                    "digest_backend": digest_backend,
                    "device": device_ok,
                    "elapsed_s": round(elapsed, 2),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.path.insert(0, ".")
    main()
